"""Pallas TPU kernels — the hand-tuned hot-op tier.

Reference analog: operators/math/jit_kernel.h:33-79 + jit_gen.h:41 — the
reference JIT-assembles x86 vector kernels (Xbyak) where the compiler's
codegen wasn't enough; on TPU that role belongs to Pallas kernels lowered
onto MXU/VPU tiles (SURVEY.md §7.9 perf closure).

Kernels: blockwise flash attention forward (online-softmax over KV blocks,
saving only the per-row logsumexp) and a fused flash-attention-2 style
backward — one kernel per K block computing dK, dV, and dQ partials, so the
score matrix and dO·Vᵀ are built once instead of twice (the classic
two-kernel split recomputes both; measured 2.4 -> 1.56 ms per fwd+grad at
t=1024 on chip). Long-context shapes stream the non-resident side through
the grid (separate dQ / dKV kernels there, where VMEM residency is the
binding constraint, not flop count). Ragged tile shapes fall back to the
dense form in both directions (a trace-time decision).

On non-TPU backends (the CPU test mesh) the kernel runs in Pallas interpret
mode — same code path, no Mosaic compile — keeping tests hermetic.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import (
    bcast_y,
    gather_op_inputs,
    register,
    register_fused,
    scatter_op_outputs,
)

__all__ = [
    "flash_attention",
    "flash_tiles_ok",
    "flash_path_taken",
    "gemm_bias_act",
    "gemm_path_taken",
    "gemm_dbuf_path_taken",
    "quant_gemm_bias_act",
    "quant_gemm_path_taken",
    "fp8_matmul",
    "paged_flash_attention",
    "paged_flash_path_taken",
    "fused_layer_norm",
    "fused_layer_norm_grad",
    "ln_path_taken",
    "multi_tensor_adam",
    "adam_path_taken",
    "KERNEL_DISPATCHES",
]

# trace-time dispatch telemetry: family -> number of times the fused lowering
# ACCEPTED a tagged run (i.e. the Pallas kernel was emitted, not the per-op
# fallback). Counted once per trace, so tests can clear() it, force a build,
# and assert the kernel path engaged (the path-assertion satellite: a ragged
# dense fallback must never silently eat the speedup).
KERNEL_DISPATCHES = {}


def _note_dispatch(family):
    KERNEL_DISPATCHES[family] = KERNEL_DISPATCHES.get(family, 0) + 1

_DEF_BLOCK_Q = 1024
_DEF_BLOCK_K = 1024
_DEF_BLOCK_Q_CAUSAL = 512
_DEF_BLOCK_K_CAUSAL = 512  # smaller K stream keeps the causal chunk-skip live
# streamed (long-context) tier optimum, swept at t=16384 on chip: (1024,1024)
# runs 100/124 TF/s eff fwd (causal/not) vs 51/63 at (512,512); same ranking
# for the backward (97/121 vs 67/90); 2048 tiles overflow VMEM
_DEF_STREAM_BLOCK = 1024
_LANES = 128  # Mosaic minimum tile width for the residual tensors


def _auto_block(t, target):
    """Largest power-of-two-scaled block ≤ target that divides t, else t
    itself when a single whole tile fits. Returns 0 for ragged shapes (the
    caller falls back to the dense form). Measured on chip (t=1024, d=128,
    b*h=128): (block_q, block_k) = (128,128) runs the forward at 21 TF/s,
    (512,1024) at 122 TF/s — the MXU needs the bigger s=(block_q, block_k)
    tiles to amortize; small defaults were the single biggest attention
    sink. Causal sweeps put (512,512) first (46→56 TF/s effective over
    (512,1024): one whole-t K block can't skip masked chunks); the backward
    shares the forward's optimum (fwd+bwd grad 2.31 ms = 104 TF/s at
    (512,1024) vs 2.68 at (512,512))."""
    c = target
    while c >= 128:
        if t % c == 0:
            return c
        c //= 2
    return t if t <= target else 0


def _resolve_blocks(block_q, block_k, causal):
    # r05 on-chip sweep (t=1024, d=128, bh=128, fused bwd): non-causal
    # (1024,1024) runs fwd+grad at 1.60 ms vs 1.77 at (512,1024); causal
    # keeps (512,512) (1.84 ms; one whole-t K block can't skip masked chunks)
    return (
        block_q or (_DEF_BLOCK_Q_CAUSAL if causal else _DEF_BLOCK_Q),
        block_k or (_DEF_BLOCK_K_CAUSAL if causal else _DEF_BLOCK_K),
    )


def _resident_ok(t, d, itemsize):
    """Whether a whole-(t, d) K and V (or q/do/lse/delta) residency fits the
    ~16 MiB VMEM budget with room for tiles and double-buffering. Calibrated
    on chip: t=8192, d=128, bf16 (4 MiB for K+V) compiles and runs; t=16384
    overflows ("Scoped allocation ... exceeded scoped vmem limit"). Beyond
    this the streamed kernels below tile the long side through the grid."""
    return t * d * itemsize * 2 <= 4 * 1024 * 1024


def _attention_reference(q, k, v, causal, sm_scale):
    """Dense XLA attention — the numerics contract and the vjp source."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k, causal,
                  sm_scale, q_block_idx_axis, t_q_total, lse_packed=True):
    """One (batch*head, q_block) program: stream KV blocks with the online
    softmax recurrence (m = running max, l = running sum, acc = running PV)."""
    qi = pl.program_id(q_block_idx_axis)
    # operands stay in their native dtype (bf16 on the train path): the MXU
    # multiplies bf16 pairs at full rate and accumulates f32 via
    # preferred_element_type — upcasting to f32 FIRST forces the multi-pass
    # f32 MXU emulation at a fraction of peak (measured: the whole fwd
    # kernel 131 -> 178 TF/s from this change alone)
    q = q_ref[...]  # (block_q, d)
    block_q = q.shape[0]
    t_k = k_ref.shape[0]
    nk = pl.cdiv(t_k, block_k)

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (block_q, block_k)
        if causal:
            # bottom-right alignment (same contract as _attention_reference's
            # tril(k=tk-tq)): query row i may see keys up to i + (tk - tq)
            offset = t_k - t_q_total
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        # -inf rows (fully masked so far) must not poison the rescale
        alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        # p rounds to v's dtype for the PV dot — the same rounding the dense
        # XLA chain applies (probs.astype(q.dtype) in _attention_reference)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    d = q.shape[1]
    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q,), -jnp.inf, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    if causal:
        # only KV blocks reaching this q block's last visible key contribute
        last_key = qi * block_q + block_q - 1 + (t_k - t_q_total)
        nk_needed = jnp.clip((last_key + block_k) // block_k, 0, nk)
    else:
        nk_needed = nk
    acc, m, l = jax.lax.fori_loop(0, nk_needed, body, init)
    o_ref[...] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        # logsumexp residual for the flash backward, PACKED as a (1, t_q)
        # lane-major row per (b*h) — the earlier 128-lane broadcast layout
        # cost ~67 MB of HBM write+read per bench attention layer where
        # this is ~0.5 MB (the relayout from the row-reduction's sublane
        # vector is a cheap in-register transpose). Fully-masked rows get
        # a finite sentinel; their p = exp(-inf - lse) is 0 either way.
        lse = jnp.where(m == -jnp.inf, 0.0, m + jnp.log(jnp.maximum(l, 1e-20)))
        if lse_packed:
            lse_ref[0, pl.ds(qi * block_q, block_q)] = lse.astype(lse_ref.dtype)
        else:
            # sub-128-lane t: Mosaic cannot vector-store partial lanes, so
            # tiny shapes keep the 128-lane broadcast residual layout
            lse_ref[...] = jnp.broadcast_to(
                lse[:, None], lse_ref.shape
            ).astype(lse_ref.dtype)


def _flash_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                           m_ref, l_ref, *, causal, sm_scale, t_q_total,
                           t_k_total, with_lse, lse_packed=True):
    """Long-context forward: grid (bh, q_blocks, k_blocks) with K/V streamed
    through the innermost grid dim, so VMEM holds one (block_q, d) query tile
    plus one (block_k, d) K/V tile regardless of t — the whole-KV-resident
    kernel above overflows VMEM past ~8k tokens (see _resident_ok). The
    online-softmax state (acc, m, l) lives in f32 VMEM scratch across the
    k-block sweep; the output tile is written on the last k step."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        offset = t_k_total - t_q_total
        needed = ki * block_k <= qi * block_q + block_q - 1 + offset
    else:
        needed = qi >= 0  # trivially true, keeps pl.when uniform

    @pl.when(needed)
    def _step():
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + (t_k_total - t_q_total) >= k_pos, s, -jnp.inf)
        m_prev = m_ref[..., 0]
        l_prev = l_ref[..., 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        m = m_ref[..., 0]
        l = l_ref[..., 0]
        o_ref[...] = (acc_ref[...] / jnp.maximum(l, 1e-20)[:, None]).astype(
            o_ref.dtype
        )
        if with_lse:
            lse = jnp.where(m == -jnp.inf, 0.0, m + jnp.log(jnp.maximum(l, 1e-20)))
            if lse_packed:
                lse_ref[0, pl.ds(qi * block_q, block_q)] = lse.astype(
                    lse_ref.dtype
                )
            else:  # sub-128-lane t_q: see _flash_kernel's note
                lse_ref[...] = jnp.broadcast_to(
                    lse[:, None], lse_ref.shape
                ).astype(lse_ref.dtype)


def _flash_forward_streamed(q3, k3, v3, causal, sm_scale, block_q, block_k,
                            interpret, with_lse, out_dtype):
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    grid = (bh, tq // block_q, tk // block_k)
    packed = tq % _LANES == 0
    out_shapes = [jax.ShapeDtypeStruct((bh, tq, d), out_dtype)]
    out_specs = [pl.BlockSpec((None, block_q, d), lambda bh, qi, ki: (bh, qi, 0))]
    if with_lse:
        if packed:
            out_shapes.append(jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32))
            out_specs.append(
                pl.BlockSpec((None, 1, tq), lambda bh, qi, ki: (bh, 0, 0))
            )
        else:
            out_shapes.append(
                jax.ShapeDtypeStruct((bh, tq, _LANES), jnp.float32)
            )
            out_specs.append(
                pl.BlockSpec(
                    (None, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0)
                )
            )
    kernel = functools.partial(
        _flash_kernel_streamed,
        causal=causal,
        sm_scale=sm_scale,
        t_q_total=tq,
        t_k_total=tk,
        with_lse=with_lse,
        lse_packed=packed,
    )
    if not with_lse:
        kernel = functools.partial(_no_lse_adapter, kernel)
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shapes if with_lse else out_shapes[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return res


def _no_lse_adapter(kernel, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
    kernel(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref)


def flash_tiles_ok(t, block=None):
    """Conservative symmetric predicate for callers that REQUIRE the Pallas
    path on a square t (the flash ring, whose merge needs the lse the dense
    fallback doesn't produce). It gates on the TIGHTEST block target across
    causal/non-causal and q/k sides (the causal 512 targets) — if it passes,
    _flash_forward takes the Pallas path for both directions in either
    mode."""
    if t <= 0:
        return False
    tightest = min(_DEF_BLOCK_Q, _DEF_BLOCK_K,
                   _DEF_BLOCK_Q_CAUSAL, _DEF_BLOCK_K_CAUSAL)
    return _auto_block(t, block or tightest) > 0


def flash_path_taken(tq, tk, causal=False, block_q=None, block_k=None):
    """EXACT mirror of _flash_forward's pallas-vs-dense decision, for code
    that must predict it from static shapes (layers.flash_attention decides
    whether to declare the Lse output with this — a mismatch would either
    dangle a declared var or silently drop the saved residual and force the
    dense recompute-vjp backward)."""
    if tq <= 0 or tk <= 0:
        return False
    bq, bk = _resolve_blocks(block_q, block_k, causal)
    return _auto_block(tq, bq) > 0 and _auto_block(tk, bk) > 0


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                   with_lse=False):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    raw_bq, raw_bk = block_q, block_k
    block_q, block_k = _resolve_blocks(block_q, block_k, causal)
    block_q = _auto_block(tq, block_q)
    block_k = _auto_block(tk, block_k)
    if not (block_q and block_k):
        # ragged tails: fall back to the dense form (shapes are static, so
        # this is a trace-time decision, not a runtime branch)
        out = _attention_reference(q, k, v, causal, sm_scale)
        return (out, None) if with_lse else out
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    if not _resident_ok(tk, d, k.dtype.itemsize):
        # long-context tier: stream K/V through the grid instead of holding
        # them whole in VMEM; the streamed optimum is larger tiles (the gate
        # above already passed, and the stream targets only widen it)
        res = _flash_forward_streamed(
            q3, k3, v3, causal, sm_scale,
            _auto_block(tq, raw_bq or _DEF_STREAM_BLOCK),
            _auto_block(tk, raw_bk or _DEF_STREAM_BLOCK),
            interpret, with_lse, q.dtype,
        )
        if with_lse:
            out, lse = res
            if tq % _LANES:
                lse = lse[..., 0]
            return out.reshape(b, h, tq, d), lse.reshape(b, h, tq)
        return res.reshape(b, h, tq, d)
    if max(tq, tk) >= 4096:
        # same VMEM clamp as the fused backward: the (1024, block_k) f32
        # score/probability temporaries + resident K/V slabs overflow VMEM
        # once EITHER side reaches t=4096 (the slabs scale with tk, the
        # temporaries with block_q*block_k — compile-checked on chip,
        # including asymmetric tq=1024/tk=4096); 512 holds through 8192
        block_q = min(block_q, 512)
    grid = (b * h, tq // block_q)
    packed = tq % _LANES == 0  # see _flash_kernel's sub-128-lane note
    out_shapes = [jax.ShapeDtypeStruct((b * h, tq, d), q.dtype)]
    out_specs = [pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0))]
    if with_lse:
        if packed:
            out_shapes.append(jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32))
            out_specs.append(pl.BlockSpec((None, 1, tq), lambda bh, qi: (bh, 0, 0)))
        else:
            out_shapes.append(
                jax.ShapeDtypeStruct((b * h, tq, _LANES), jnp.float32)
            )
            out_specs.append(
                pl.BlockSpec((None, block_q, _LANES), lambda bh, qi: (bh, qi, 0))
            )
    res = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            q_block_idx_axis=1,
            t_q_total=tq,
            lse_packed=packed,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shapes if with_lse else out_shapes[0],
        interpret=interpret,
    )(q3, k3, v3)
    if with_lse:
        out, lse = res
        if not packed:
            lse = lse[..., 0]
        return out.reshape(b, h, tq, d), lse.reshape(b, h, tq)
    return res.reshape(b, h, tq, d)




# ---------------------------------------------------------------------------
# flash backward (flash-attention-2 style): dQ in one kernel over q blocks,
# dK/dV in another over k blocks, both streaming the opposite side and using
# the saved logsumexp L plus D = rowsum(dO * O)
# ---------------------------------------------------------------------------


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                            dk_ref, dv_ref, dqp_ref, *, block_q, causal,
                            sm_scale, t_q_total, lse_packed=True):
    """Fused resident backward: one (bh, k_block) program computes dK and dV
    for its K block AND this K block's partial contribution to every dQ row
    (summed over k blocks by XLA outside). The two-kernel form recomputes the
    score matrix s and dp = dO·Vᵀ in BOTH kernels — 7 matmul-units per
    backward vs the 5 this kernel executes (s, dp, dV, dK, dQ-partial), a
    28%% flop cut on the exact tier the MFU bench runs (measured on chip:
    fwd+grad 2.42 -> 1.87 ms at t=1024 bh=128 non-causal)."""
    ki = pl.program_id(1)
    k_blk = k_ref[...]  # (block_k, d)
    v_blk = v_ref[...]
    block_k = k_blk.shape[0]
    t_k_total = pl.num_programs(1) * block_k
    offset = t_k_total - t_q_total  # bottom-right causal alignment
    t_q = q_ref.shape[0]
    nq = pl.cdiv(t_q, block_q)

    dqp_ref[...] = jnp.zeros_like(dqp_ref)  # skipped causal rows stay 0

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :]
        if lse_packed:
            lse = lse_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        else:
            lse = lse_ref[pl.ds(qi * block_q, block_q), 0].astype(jnp.float32)
        # delta = rowsum(dO * O) computed here from the saved forward output
        # rather than as an XLA prologue: the prologue form writes + re-reads
        # a 128-lane-broadcast f32 tensor per layer (~134 MB of HBM traffic)
        # where this is a VPU rowsum over tiles already resident
        o_blk = o_ref[pl.ds(qi * block_q, block_q), :]
        delta = jnp.sum(
            do_blk.astype(jnp.float32) * o_blk.astype(jnp.float32), axis=1
        )
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q_blk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dqp_ref[pl.ds(qi * block_q, block_q), :] = jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dqp_ref.dtype)
        return dk, dv

    if causal:
        first_q_row = ki * block_k - offset
        q_start = jnp.clip(first_q_row // block_q, 0, nq)
    else:
        q_start = 0
    d = k_blk.shape[1]
    dk, dv = jax.lax.fori_loop(
        q_start,
        nq,
        body,
        (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32)),
    )
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dq_ref, dq_acc, *, causal, sm_scale, t_q_total,
                           t_k_total, lse_packed=True):
    """Streamed dQ: grid (bh, q_blocks, k_blocks); K/V tiles ride the inner
    grid dim, dQ accumulates in f32 scratch and lands on the last k step."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        offset = t_k_total - t_q_total
        needed = ki * block_k <= qi * block_q + block_q - 1 + offset
    else:
        needed = qi >= 0

    @pl.when(needed)
    def _step():
        block_q_ = q_ref.shape[0]
        q = q_ref[...]
        do = do_ref[...]
        if lse_packed:
            lse = lse_ref[0, pl.ds(qi * block_q_, block_q_)].astype(jnp.float32)
            delta = delta_ref[0, pl.ds(qi * block_q_, block_q_)].astype(
                jnp.float32
            )
        else:  # per-q-block 128-lane broadcast layout (sub-128-lane t_q)
            lse = lse_ref[..., 0].astype(jnp.float32)
            delta = delta_ref[..., 0].astype(jnp.float32)
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + (t_k_total - t_q_total) >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
        dq_acc[...] = dq_acc[...] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dk_ref, dv_ref, dk_acc, dv_acc, *, causal,
                            sm_scale, t_q_total, t_k_total, lse_packed=True):
    """Streamed dK/dV: grid (bh, k_blocks, q_blocks); Q/dO/lse/delta tiles
    ride the inner grid dim, dK/dV accumulate in f32 scratch."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    offset = t_k_total - t_q_total
    if causal:
        # q rows before this k block's first key see nothing of it
        needed = qi * block_q + block_q - 1 + offset >= ki * block_k
    else:
        needed = qi >= 0

    @pl.when(needed)
    def _step():
        block_q_ = q_ref.shape[0]
        q_blk = q_ref[...]
        do_blk = do_ref[...]
        if lse_packed:
            lse = lse_ref[0, pl.ds(qi * block_q_, block_q_)].astype(jnp.float32)
            delta = delta_ref[0, pl.ds(qi * block_q_, block_q_)].astype(
                jnp.float32
            )
        else:
            lse = lse_ref[..., 0].astype(jnp.float32)
            delta = delta_ref[..., 0].astype(jnp.float32)
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q_blk.dtype)
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward_streamed(q3, k3, v3, do3, lse3, delta, causal, sm_scale,
                             block_q, block_k, interpret, out_dtypes):
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    packed = tq % _LANES == 0  # lse3/delta arrive in the matching layout
    q_spec = pl.BlockSpec((None, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    k_spec = pl.BlockSpec((None, block_k, d), lambda bh, qi, ki: (bh, ki, 0))
    lane_q = (
        pl.BlockSpec((None, 1, tq), lambda bh, qi, ki: (bh, 0, 0))
        if packed
        else pl.BlockSpec((None, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0))
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_streamed,
            causal=causal, sm_scale=sm_scale, t_q_total=tq, t_k_total=tk,
            lse_packed=packed,
        ),
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, lane_q, lane_q],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), out_dtypes[0]),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta)

    kq_spec = pl.BlockSpec((None, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    kk_spec = pl.BlockSpec((None, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    klane_q = (
        pl.BlockSpec((None, 1, tq), lambda bh, ki, qi: (bh, 0, 0))
        if packed
        else pl.BlockSpec((None, block_q, _LANES), lambda bh, ki, qi: (bh, qi, 0))
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_streamed,
            causal=causal, sm_scale=sm_scale, t_q_total=tq, t_k_total=tk,
            lse_packed=packed,
        ),
        grid=(bh, tk // block_k, tq // block_q),
        in_specs=[kq_spec, kk_spec, kk_spec, kq_spec, klane_q, klane_q],
        out_specs=[kk_spec, kk_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), out_dtypes[1]),
            jax.ShapeDtypeStruct((bh, tk, d), out_dtypes[2]),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta)
    return dq, dk, dv


def _flash_backward(q, k, v, out, lse, dout, causal, sm_scale, block_q,
                    block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    raw_bq, raw_bk = block_q, block_k
    block_q, block_k = _resolve_blocks(block_q, block_k, causal)
    block_q = _auto_block(tq, block_q)
    block_k = _auto_block(tk, block_k)
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    do3 = dout.reshape(b * h, tq, d)
    packed = tq % _LANES == 0  # matches the forward's residual layout rule
    if packed:
        lse3 = lse.reshape(b * h, 1, tq)
    else:
        lse3 = jnp.broadcast_to(
            lse.reshape(b * h, tq)[..., None], (b * h, tq, _LANES)
        )

    # the fused kernel needs whole-side VMEM residency (breaks past ~8k
    # tokens) and materializes an (nk, tq, d) dQ-partials HBM temporary —
    # bounded to <=2x dQ by the nk cap here; everything bigger takes the
    # grid-streamed two-kernel tier (any t, O(t) memory)
    if tk // block_k > 2 or not (
        _resident_ok(tk, d, k.dtype.itemsize)
        and _resident_ok(tq, d, q.dtype.itemsize)
    ):
        delta = jnp.sum(
            dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        )
        if packed:
            delta = delta.reshape(b * h, 1, tq)
        else:  # must mirror lse3's layout — the kernels' specs follow it
            delta = jnp.broadcast_to(
                delta.reshape(b * h, tq)[..., None], (b * h, tq, _LANES)
            )
        dq, dk, dv = _flash_backward_streamed(
            q3, k3, v3, do3, lse3, delta, causal, sm_scale,
            _auto_block(tq, raw_bq or _DEF_STREAM_BLOCK),
            _auto_block(tk, raw_bk or _DEF_STREAM_BLOCK),
            interpret, (q.dtype, k.dtype, v.dtype),
        )
        return (
            dq.reshape(b, h, tq, d),
            dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d),
        )

    if max(tq, tk) >= 4096:
        # the fused kernel's f32 score/probability temporaries at
        # block_q=1024 overflow VMEM once the resident slabs (q/do/o with
        # tq, K/V with tk) reach t=4096 (compile-checked on chip); 512
        # holds through t=8192
        block_q = min(block_q, 512)
    nk = tk // block_k
    dk, dv, dqp = pl.pallas_call(
        functools.partial(
            _flash_bwd_fused_kernel,
            block_q=block_q,
            causal=causal,
            sm_scale=sm_scale,
            t_q_total=tq,
            lse_packed=packed,
        ),
        grid=(b * h, nk),
        in_specs=[
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            (
                pl.BlockSpec((None, 1, tq), lambda bh, ki: (bh, 0, 0))
                if packed
                else pl.BlockSpec((None, tq, _LANES), lambda bh, ki: (bh, 0, 0))
            ),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, None, tq, d), lambda bh, ki: (bh, ki, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
            # dQ partials, one slab per k block, in q's dtype: each partial
            # is already f32-accumulated inside its dot; the cross-block sum
            # over nk<=2 terms (the tier gate above routes tk//block_k > 2
            # to the streamed path) loses nothing the final bf16 cast keeps
            jax.ShapeDtypeStruct((b * h, nk, tq, d), q.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, out.reshape(b * h, tq, d), lse3)
    dq = (
        dqp[:, 0]
        if nk == 1
        else jnp.sum(dqp, axis=1, dtype=jnp.float32).astype(q.dtype)
    )

    return (
        dq.reshape(b, h, tq, d),
        dk.reshape(b, h, tk, d),
        dv.reshape(b, h, tk, d),
    )


def _resolve_defaults(q, sm_scale, interpret):
    """Single source of the defaulting rule: forward, _fwd and _bwd must
    agree or a custom_vjp would silently produce wrong gradients."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return sm_scale, interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    block_q=None,
    block_k=None,
    interpret=None,
):
    """softmax(QKᵀ·scale [causal-masked]) V over (b, h, t, d) tensors.

    block_q/block_k of None pick tuned per-direction defaults adapted to the
    sequence length (_auto_block); explicit values act as upper-bound targets.
    """
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    res = _flash_forward(
        q, k, v, causal, sm_scale, block_q, block_k, interpret, with_lse=True
    )
    out, lse = res
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    if lse is None:
        # ragged-tail fallback: dense recompute-vjp (same trace-time decision
        # as the forward fallback)
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale), q, k, v
        )
        return vjp(dout)
    return _flash_backward(
        q, k, v, out, lse, dout, causal, sm_scale, block_q, block_k, interpret
    )


flash_attention.defvjp(_fwd, _bwd)


@register("flash_attention")
def _flash_attention_op(ctx, ins, attrs):
    """Graph-op form: Q/K/V (b, h, t, d) → Out (+ Lse residual). The
    transformer layers emit this in place of the matmul+softmax+matmul chain.

    The logsumexp residual is emitted as a side output so the explicit
    flash_attention_grad below can run the flash backward against the SAVED
    forward — without it, the generic vjp-derived grad re-traces the forward
    inside jax.vjp, and since the duplicate is a pallas custom-call with a
    different output arity, XLA CSE cannot deduplicate it (one extra forward
    kernel run per attention block per step, measured on chip)."""
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    causal = bool(attrs.get("causal", False))
    sm_scale, interpret = _resolve_defaults(q, attrs.get("sm_scale"), None)
    out, lse = _flash_forward(
        q, k, v, causal, sm_scale, None, None, interpret, with_lse=True
    )
    res = {"Out": [out]}
    if lse is not None:
        res["Lse"] = [lse]
    return res


@register("flash_attention_grad", no_grad=True)
def _flash_attention_grad_op(ctx, ins, attrs):
    """Explicit grad: flash backward kernels against the saved Out/Lse.
    Falls back to the dense recompute-vjp when the forward took the dense
    path (no Lse in the program — ragged tiles)."""
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    (dout,) = ins["Out@GRAD"]
    causal = bool(attrs.get("causal", False))
    sm_scale, interpret = _resolve_defaults(q, attrs.get("sm_scale"), None)
    lse = ins.get("Lse", [None])[0]
    if lse is None:
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale),
            q, k, v,
        )
        dq, dk, dv = vjp(dout.astype(q.dtype))
    else:
        (out,) = ins["Out"]
        dq, dk, dv = _flash_backward(
            q, k, v, out, lse, dout, causal, sm_scale, None, None, interpret
        )
    return {"Q@GRAD": [dq], "K@GRAD": [dk], "V@GRAD": [dv]}


# ---------------------------------------------------------------------------
# kernel-substitution tier: fused GEMM epilogue, fused layer_norm(+residual),
# and multi-tensor Adam. Each is reached through a `fuse_*` pass
# (passes/builtin.py) that tags op runs with PALLAS_GROUP_ATTR /
# PALLAS_KERNEL_ATTR; registry.lower_ops hands a tagged run to the
# @register_fused lowering below, which validates shapes/attrs at TRACE time
# and declines (return False -> per-op fallback) anything the kernel can't
# take — so tagging is always semantics-preserving.
# ---------------------------------------------------------------------------

# r06 on-chip sweep (m=8192, n=k=2048, bf16): (512,512,512) tiles run the
# fused GEMM+bias+gelu at 168 TF/s vs 141 at (256,256,512) and 155 at
# (512,512,256) — the MXU wants the large accumulate tile, and k=512 keeps
# the x/w stream double-buffered under the ~16 MiB VMEM roof
_DEF_GEMM_BLOCK_M = 512
_DEF_GEMM_BLOCK_N = 512
_DEF_GEMM_BLOCK_K = 512

# epilogue activations the kernel computes on the f32 accumulator before the
# single rounding to the output dtype; must stay the exact functions
# core_ops registers (gelu is the erf form, approximate=False) or fused/
# unfused parity drifts beyond rounding
_GEMM_ACT_F32 = {
    "relu": lambda z: jnp.maximum(z, 0.0),
    "gelu": lambda z: jax.nn.gelu(z, approximate=False),
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}


def gemm_path_taken(m, n, k, block_m=None, block_n=None, block_k=None):
    """EXACT mirror of gemm_bias_act's pallas-vs-dense decision (the
    flash_path_taken idiom): tests assert it, and the fused lowering declines
    a tagged chain when it is False so the dense per-op path keeps parity."""
    if m <= 0 or n <= 0 or k <= 0:
        return False
    return (
        _auto_block(m, block_m or _DEF_GEMM_BLOCK_M) > 0
        and _auto_block(n, block_n or _DEF_GEMM_BLOCK_N) > 0
        and _auto_block(k, block_k or _DEF_GEMM_BLOCK_K) > 0
    )


def gemm_dbuf_path_taken(m, n, k, block_m=None, block_n=None, block_k=None):
    """Mirror of the double-buffered-GEMM dispatch: the manual k-loop DMA
    kernel runs exactly when the ordinary tiled kernel would (same tile
    feasibility — the accumulation order is identical, so outputs are
    bit-identical) AND the gemm_double_buffer flag takes it: "on" forces it
    everywhere (interpret-mode parity tests), "auto" takes it only on a real
    TPU (manual DMA emulation underperforms the pipelined form on the CPU
    interpreter), "off" keeps the grid-pipelined kernel."""
    from .. import flags as _flags

    mode = _flags.get_flags("gemm_double_buffer")["gemm_double_buffer"]
    if mode == "off":
        return False
    if not gemm_path_taken(m, n, k, block_m, block_n, block_k):
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


def _gemm_dbuf_kernel(x_hbm, w_hbm, b_ref, z_ref, y_ref, xb, wb, acc_ref,
                      sem, *, act, bm, bn, bk, nk):
    """One (m_block, n_block) output tile with an explicit double-buffered
    k loop: x/w stay HBM-resident (memory_space=ANY) and the kernel DMAs
    tile k+1 into the spare VMEM slot while the MXU contracts tile k — the
    overlap the grid-pipelined form leaves to the emitter, written out by
    hand so the k stream never stalls on the copy. Accumulation order and
    epilogue are identical to _gemm_epilogue_kernel (bit-identical parity
    is asserted by tests)."""
    mi = pl.program_id(0)
    ni = pl.program_id(1)

    def tile_in(ki, slot):
        cx = pltpu.make_async_copy(
            x_hbm.at[pl.ds(mi * bm, bm), pl.ds(ki * bk, bk)],
            xb.at[slot], sem.at[slot, 0],
        )
        cw = pltpu.make_async_copy(
            w_hbm.at[pl.ds(ki * bk, bk), pl.ds(ni * bn, bn)],
            wb.at[slot], sem.at[slot, 1],
        )
        return cx, cw

    acc_ref[...] = jnp.zeros_like(acc_ref)
    for c in tile_in(0, 0):
        c.start()

    def body(ki, _):
        slot = jax.lax.rem(ki, 2)

        @pl.when(ki + 1 < nk)
        def _prefetch():
            for c in tile_in(ki + 1, 1 - slot):
                c.start()

        for c in tile_in(ki, slot):
            c.wait()
        acc_ref[...] += jax.lax.dot_general(
            xb[slot], wb[slot], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return 0

    jax.lax.fori_loop(0, nk, body, 0)
    z = acc_ref[...] + b_ref[...].astype(jnp.float32)
    z_ref[...] = z.astype(z_ref.dtype)
    if y_ref is not None:
        y_ref[...] = _GEMM_ACT_F32[act](z).astype(y_ref.dtype)


def _gemm_dbuf_no_act_adapter(kernel, x_hbm, w_hbm, b_ref, z_ref, xb, wb,
                              acc_ref, sem):
    kernel(x_hbm, w_hbm, b_ref, z_ref, None, xb, wb, acc_ref, sem)


def _gemm_bias_act_dbuf(x2, w2, bias_row, act, bm, bn, bk, interpret):
    m, k = x2.shape
    n = w2.shape[1]
    grid = (m // bm, n // bn)
    nk = k // bk
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec((1, bn), lambda mi, ni: (0, ni)),
    ]
    out_spec = pl.BlockSpec((bm, bn), lambda mi, ni: (mi, ni))
    scratch = [
        pltpu.VMEM((2, bm, bk), x2.dtype),
        pltpu.VMEM((2, bk, bn), w2.dtype),
        pltpu.VMEM((bm, bn), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    kernel = functools.partial(
        _gemm_dbuf_kernel, act=act, bm=bm, bn=bn, bk=bk, nk=nk
    )
    cost = pl.CostEstimate(
        flops=2 * m * n * k,
        bytes_accessed=(x2.size + w2.size) * x2.dtype.itemsize
        + (2 if act else 1) * m * n * x2.dtype.itemsize,
        transcendentals=m * n if act in ("gelu", "tanh", "sigmoid") else 0,
    )
    if act is None:
        z = pl.pallas_call(
            functools.partial(_gemm_dbuf_no_act_adapter, kernel),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
            scratch_shapes=scratch,
            cost_estimate=cost,
            interpret=interpret,
        )(x2, w2, bias_row)
        return z, None
    z, y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x2.dtype),
            jax.ShapeDtypeStruct((m, n), x2.dtype),
        ],
        scratch_shapes=scratch,
        cost_estimate=cost,
        interpret=interpret,
    )(x2, w2, bias_row)
    return z, y


def _gemm_epilogue_kernel(x_ref, w_ref, b_ref, z_ref, y_ref, acc_ref, *, act):
    """One (m_block, n_block) output tile: stream k blocks through the
    innermost grid dim into an f32 VMEM accumulator; on the last k step add
    the bias row and apply the activation on the f32 value, rounding ONCE to
    the output dtype (the dense chain rounds after the matmul, the add, and
    the act — the documented fused-vs-unfused bf16 tolerance)."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == nk - 1)
    def _finish():
        z = acc_ref[...] + b_ref[...].astype(jnp.float32)
        z_ref[...] = z.astype(z_ref.dtype)
        if y_ref is not None:
            y_ref[...] = _GEMM_ACT_F32[act](z).astype(y_ref.dtype)


def _gemm_no_act_adapter(kernel, x_ref, w_ref, b_ref, z_ref, acc_ref):
    kernel(x_ref, w_ref, b_ref, z_ref, None, acc_ref)


def gemm_bias_act(x2, w2, bias_row, act=None, *, block_m=None, block_n=None,
                  block_k=None, interpret=None):
    """act(x2 @ w2 + bias) over 2-D operands with the bias+activation fused
    into the GEMM epilogue. bias_row is (1, n) (or broadcastable to it).
    Returns (z, y): z the post-bias pre-activation value, y = act(z) (None
    when act is None). Ragged tiles fall back to a dense XLA form with the
    SAME f32-accumulate/round-once numerics (trace-time decision)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x2.shape
    n = w2.shape[1]
    bias_row = jnp.broadcast_to(bias_row.reshape(1, -1), (1, n))
    if not gemm_path_taken(m, n, k, block_m, block_n, block_k):
        z32 = jax.lax.dot_general(
            x2, w2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + bias_row.astype(jnp.float32)
        z = z32.astype(x2.dtype)
        y = _GEMM_ACT_F32[act](z32).astype(x2.dtype) if act else None
        return z, y
    bm = _auto_block(m, block_m or _DEF_GEMM_BLOCK_M)
    bn = _auto_block(n, block_n or _DEF_GEMM_BLOCK_N)
    bk = _auto_block(k, block_k or _DEF_GEMM_BLOCK_K)
    if gemm_dbuf_path_taken(m, n, k, block_m, block_n, block_k):
        _note_dispatch("gemm_dbuf")
        return _gemm_bias_act_dbuf(x2, w2, bias_row, act, bm, bn, bk, interpret)
    grid = (m // bm, n // bn, k // bk)  # k innermost: acc carries across it
    in_specs = [
        pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
        pl.BlockSpec((bk, bn), lambda mi, ni, ki: (ki, ni)),
        pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
    ]
    out_spec = pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni))
    kernel = functools.partial(_gemm_epilogue_kernel, act=act)
    cost = pl.CostEstimate(
        flops=2 * m * n * k,
        bytes_accessed=(x2.size + w2.size) * x2.dtype.itemsize
        + (2 if act else 1) * m * n * x2.dtype.itemsize,
        transcendentals=m * n if act in ("gelu", "tanh", "sigmoid") else 0,
    )
    if act is None:
        z = pl.pallas_call(
            functools.partial(_gemm_no_act_adapter, kernel),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            cost_estimate=cost,
            interpret=interpret,
        )(x2, w2, bias_row)
        return z, None
    z, y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x2.dtype),
            jax.ShapeDtypeStruct((m, n), x2.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=cost,
        interpret=interpret,
    )(x2, w2, bias_row)
    return z, y


# ---------------------------------------------------------------------------
# quantized GEMM tier — int8×int8→i32 and fp8(e4m3)×fp8→f32 tile paths over
# the same (m, n, k) grid as gemm_bias_act. The MXU contracts the low-
# precision operands natively (v5e: 383 int8 TOPS vs 192 bf16 TF/s — 2×) and
# the dequantize multiply rides the existing epilogue: acc → ·scale → +bias
# → act, rounding ONCE at the store exactly like the f32 path. Uncovered
# shapes/dtypes decline to a dense XLA form with the same
# low-precision-multiply / wide-accumulate / round-once numerics, so the
# dispatch decision never changes results (the PR 11 contract). int8's i32
# accumulation is exact regardless of tiling; kernel-vs-fallback parity is
# within one f32 ulp of the dequant epilogue (the compiler may or may not
# fuse ·scale+bias into an fma). fp8's f32 accumulation is tiled, so parity
# is bit-bounded like flash.
# ---------------------------------------------------------------------------

# int8/fp8 sublane minimum is 32 (vs 8 for f32, 16 for bf16) — a (32, 128)
# tile floor. _auto_block's 128 floor already clears it; the 512 target from
# the r06 f32 sweep carries over (the accumulate tile, not the operand dtype,
# is what the MXU wants large).
_QUANT_GEMM_ACC = {
    jnp.dtype(jnp.int8): jnp.int32,
    jnp.dtype(jnp.float8_e4m3fn): jnp.float32,
}


def quant_gemm_path_taken(m, n, k, dtype, block_m=None, block_n=None,
                          block_k=None):
    """EXACT mirror of quant_gemm_bias_act's pallas-vs-dense decision. The
    quantized_gemm flag picks the tier with the paged_flash semantics: "off"
    always dense, "on" forces the kernel (interpret mode off-TPU — parity
    tests), "auto" takes the kernel only on a real TPU (an interpreted
    int8 kernel is slower than the dense XLA dot on the CPU test mesh).
    dtype must be int8 or float8_e4m3fn and the f32-GEMM tile feasibility
    applies unchanged."""
    from .. import flags as _flags

    mode = _flags.get_flags("quantized_gemm")["quantized_gemm"]
    if mode == "off":
        return False
    if jnp.dtype(dtype) not in _QUANT_GEMM_ACC:
        return False
    if not gemm_path_taken(m, n, k, block_m, block_n, block_k):
        return False
    # low-precision Mosaic granule is (32, 128) — stricter than the f32
    # tier, which accepts a single whole ragged tile
    bm = _auto_block(m, block_m or _DEF_GEMM_BLOCK_M)
    bn = _auto_block(n, block_n or _DEF_GEMM_BLOCK_N)
    bk = _auto_block(k, block_k or _DEF_GEMM_BLOCK_K)
    if bm % 32 or bn % _LANES or bk % _LANES:
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


def _quant_gemm_kernel(s_ref, x_ref, w_ref, b_ref, z_ref, y_ref, acc_ref, *,
                       act):
    """One (m_block, n_block) tile: low-precision operands stream through the
    MXU into a wide VMEM accumulator (i32 for int8, f32 for fp8 — native-
    dtype operands with preferred_element_type, never upcast first); the last
    k step dequantizes with the combined per-tensor scale, adds bias, applies
    the activation, and rounds once to the output dtype. The scale rides in
    SMEM as a (1, 1) scalar."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=acc_ref.dtype,
    )

    @pl.when(ki == nk - 1)
    def _finish():
        z = acc_ref[...].astype(jnp.float32) * s_ref[0, 0] + b_ref[
            ...
        ].astype(jnp.float32)
        z_ref[...] = z.astype(z_ref.dtype)
        if y_ref is not None:
            y_ref[...] = _GEMM_ACT_F32[act](z).astype(y_ref.dtype)


def _quant_gemm_no_act_adapter(kernel, s_ref, x_ref, w_ref, b_ref, z_ref,
                               acc_ref):
    kernel(s_ref, x_ref, w_ref, b_ref, z_ref, None, acc_ref)


def quant_gemm_bias_act(x2, w2, scale, bias_row=None, act=None, *,
                        out_dtype=jnp.float32, block_m=None, block_n=None,
                        block_k=None, interpret=None):
    """act((x2 @ w2) * scale + bias) where x2/w2 are int8 levels or fp8
    values and scale is the combined per-tensor dequantize factor
    (x_scale * w_scale, a scalar). Accumulation is i32 (int8) or f32 (fp8);
    dequant/bias/act happen on the wide value with ONE rounding to out_dtype.
    Returns (z, y) like gemm_bias_act: z post-bias pre-activation, y = act(z)
    (None when act is None). Shapes/dtypes the kernel declines
    (quant_gemm_path_taken False) fall back to a dense XLA form with the
    same wide-accumulate/round-once numerics."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x2.shape
    n = w2.shape[1]
    acc_dtype = _QUANT_GEMM_ACC.get(jnp.dtype(x2.dtype))
    scale = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    if bias_row is None:
        bias_row = jnp.zeros((1, n), jnp.float32)
    bias_row = jnp.broadcast_to(bias_row.reshape(1, -1), (1, n))
    if acc_dtype is None or not quant_gemm_path_taken(
        m, n, k, x2.dtype, block_m, block_n, block_k
    ):
        wide = jax.lax.dot_general(
            x2, w2, (((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype or jnp.float32,
        )
        z32 = wide.astype(jnp.float32) * scale[0, 0] + bias_row.astype(
            jnp.float32
        )
        z = z32.astype(out_dtype)
        y = _GEMM_ACT_F32[act](z32).astype(out_dtype) if act else None
        return z, y
    family = "gemm_int8" if acc_dtype == jnp.int32 else "gemm_fp8"
    _note_dispatch(family)
    bm = _auto_block(m, block_m or _DEF_GEMM_BLOCK_M)
    bn = _auto_block(n, block_n or _DEF_GEMM_BLOCK_N)
    bk = _auto_block(k, block_k or _DEF_GEMM_BLOCK_K)
    grid = (m // bm, n // bn, k // bk)  # k innermost: acc carries across it
    in_specs = [
        pl.BlockSpec(
            (1, 1), lambda mi, ni, ki: (0, 0), memory_space=pltpu.SMEM
        ),
        pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
        pl.BlockSpec((bk, bn), lambda mi, ni, ki: (ki, ni)),
        pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
    ]
    out_spec = pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni))
    kernel = functools.partial(_quant_gemm_kernel, act=act)
    cost = pl.CostEstimate(
        flops=2 * m * n * k,
        bytes_accessed=(x2.size + w2.size) * x2.dtype.itemsize
        + (2 if act else 1) * m * n * jnp.dtype(out_dtype).itemsize,
        transcendentals=m * n if act in ("gelu", "tanh", "sigmoid") else 0,
    )
    scratch = [pltpu.VMEM((bm, bn), acc_dtype)]
    if act is None:
        z = pl.pallas_call(
            functools.partial(_quant_gemm_no_act_adapter, kernel),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
            scratch_shapes=scratch,
            cost_estimate=cost,
            interpret=interpret,
        )(scale, x2, w2, bias_row)
        return z, None
    z, y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), out_dtype),
            jax.ShapeDtypeStruct((m, n), out_dtype),
        ],
        scratch_shapes=scratch,
        cost_estimate=cost,
        interpret=interpret,
    )(scale, x2, w2, bias_row)
    return z, y


def fp8_matmul(x, y):
    """Training-matmul fp8 tier (FLAGS_fp8_matmul): cast both operands to
    float8_e4m3fn, contract on the MXU with f32 accumulation, and return in
    the input dtype. One rounding per operand plus the output cast — the
    delayed-scaling recipes keep amax history per tensor; this is the
    simpler static cast form, enough for the BENCH step-time entry (the MXU
    runs e4m3×e4m3 at the int8 rate). Shapes are unrestricted: this is a
    dtype policy, not a kernel, so XLA owns the tiling."""
    _note_dispatch("matmul_fp8")
    f8 = jnp.float8_e4m3fn
    out = jnp.matmul(
        x.astype(f8), y.astype(f8), preferred_element_type=jnp.float32
    )
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# paged flash attention — the serving decode/chunk-prefill kernel. Walks a
# slot's block table page by page with the online-softmax recurrence in a
# VMEM accumulator, reading K/V pages straight out of the paged pool and
# masking by position inside the loop — the gathered [*, ctx, heads, d]
# context of the dense lowering is never materialized.
# ---------------------------------------------------------------------------


def paged_flash_path_taken(n_q, n_pages, page_size, n_head, d_head):
    """EXACT mirror of the paged_attention lowering's kernel-vs-dense
    decision. The paged_flash flag picks the tier: "off" always takes the
    dense flat-gather reference; "on" always takes the kernel (interpret
    mode off-TPU — the hermetic parity tests force this); "auto" (default)
    takes the kernel only on a real TPU, because an interpreted Pallas body
    in the decode hot loop is slower than the dense XLA gather on the CPU
    test mesh. Geometry beyond this never declines: the kernel walks any
    (pages, page_size, heads) layout page by page."""
    from .. import flags as _flags

    mode = _flags.get_flags("paged_flash")["paged_flash"]
    if mode == "off":
        return False
    if min(int(n_q), int(n_pages), int(page_size), int(n_head), int(d_head)) < 1:
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


def _paged_flash_update(s, live, v2, acc_ref, m_ref, l_ref):
    """One page's online-softmax step. s is [rows, page_size] f32 scores
    (masked entries already -inf), live the same-shaped mask, v2 the page's
    [page_size, d] V rows. Carries (m, l, acc) live in VMEM scratch; m/l are
    lane-broadcast like the flash kernels above."""
    m_prev = m_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # alpha rescales the old accumulator; exp(-inf - -inf) = nan, so pin
    # never-seen rows (m_prev = -inf) to alpha = 0 explicitly
    alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
    p = jnp.exp(s - m_new)
    p = jnp.where(live, p, 0.0)  # kills the -inf - -inf nan on dead rows too
    l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v2, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)


def _paged_flash_emit(o_ref, acc_ref, l_ref):
    # safe softmax tail: a fully-masked row (pos < 0, nothing live) has
    # l = 0 and emits zeros instead of 0/0 nan — the where-mask contract
    # the dense reference shares
    l = l_ref[:, :1]
    o_ref[...] = (acc_ref[...] / jnp.where(l > 0.0, l, 1.0))[:, None, :].astype(
        o_ref.dtype
    )


def _paged_flash_decode_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                               acc_ref, m_ref, l_ref, *, page_size, sm_scale):
    """grid (slot, head, page): per-slot block tables, one query row each.
    The block table rides in as a scalar-prefetch operand so the K/V
    BlockSpec index_map can chase pages; pos masks inside the loop."""
    si = pl.program_id(0)
    pi = pl.program_id(2)
    pos = pos_ref[si]

    @pl.when(pi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(pi * page_size <= pos)  # pages wholly past pos: skip the MXU
    def _page():
        q2 = q_ref[:, 0, :]  # (1, d)
        k2 = k_ref[:, 0, :]  # (page_size, d)
        s = jax.lax.dot_general(
            q2, k2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (1, page_size)
        offs = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        live = offs <= pos
        s = jnp.where(live, s, -jnp.inf)
        _paged_flash_update(s, live, v_ref[:, 0, :], acc_ref, m_ref, l_ref)

    @pl.when(pi == pl.num_programs(2) - 1)
    def _emit():
        _paged_flash_emit(o_ref, acc_ref, l_ref)


def _paged_flash_shared_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                               acc_ref, m_ref, l_ref, *, page_size, sm_scale):
    """grid (head, page): ONE block table shared by every query row (the
    chunked-prefill form — a chunk's rows all walk the same slot's pages),
    so each page is streamed into VMEM once for all rows instead of once
    per row."""
    pi = pl.program_id(1)
    pos = pos_ref[...]  # (rows,)

    @pl.when(pi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(pi * page_size <= jnp.max(pos))
    def _page():
        q2 = q_ref[:, 0, :]  # (rows, d)
        k2 = k_ref[:, 0, :]  # (page_size, d)
        s = jax.lax.dot_general(
            q2, k2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (rows, page_size)
        offs = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        live = offs <= pos[:, None]
        s = jnp.where(live, s, -jnp.inf)
        _paged_flash_update(s, live, v_ref[:, 0, :], acc_ref, m_ref, l_ref)

    @pl.when(pi == pl.num_programs(1) - 1)
    def _emit():
        _paged_flash_emit(o_ref, acc_ref, l_ref)


def _paged_flash_decode_quant_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref,
                                     ks_ref, vs_ref, o_ref, acc_ref, m_ref,
                                     l_ref, *, page_size, sm_scale):
    """int8-pool twin of _paged_flash_decode_kernel: K/V pages arrive as
    int8 levels plus a per-row f32 scale vector per page (chasing the same
    block table), and the dequantize multiply happens in VMEM on the page
    walk — the f32 rows never exist in HBM, which is the whole point (the
    pool at half the bytes holds twice the slots)."""
    si = pl.program_id(0)
    pi = pl.program_id(2)
    pos = pos_ref[si]

    @pl.when(pi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(pi * page_size <= pos)
    def _page():
        q2 = q_ref[:, 0, :].astype(jnp.float32)  # (1, d)
        k2 = k_ref[:, 0, :].astype(jnp.float32) * ks_ref[0, :][:, None]
        v2 = v_ref[:, 0, :].astype(jnp.float32) * vs_ref[0, :][:, None]
        s = jax.lax.dot_general(
            q2, k2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        offs = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        live = offs <= pos
        s = jnp.where(live, s, -jnp.inf)
        _paged_flash_update(s, live, v2, acc_ref, m_ref, l_ref)

    @pl.when(pi == pl.num_programs(2) - 1)
    def _emit():
        _paged_flash_emit(o_ref, acc_ref, l_ref)


def _paged_flash_shared_quant_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref,
                                     ks_ref, vs_ref, o_ref, acc_ref, m_ref,
                                     l_ref, *, page_size, sm_scale):
    """int8-pool twin of _paged_flash_shared_kernel (chunked prefill — one
    block table, one scale vector per page shared by every row)."""
    pi = pl.program_id(1)
    pos = pos_ref[...]  # (rows,)

    @pl.when(pi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(pi * page_size <= jnp.max(pos))
    def _page():
        q2 = q_ref[:, 0, :].astype(jnp.float32)  # (rows, d)
        k2 = k_ref[:, 0, :].astype(jnp.float32) * ks_ref[0, :][:, None]
        v2 = v_ref[:, 0, :].astype(jnp.float32) * vs_ref[0, :][:, None]
        s = jax.lax.dot_general(
            q2, k2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        offs = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        live = offs <= pos[:, None]
        s = jnp.where(live, s, -jnp.inf)
        _paged_flash_update(s, live, v2, acc_ref, m_ref, l_ref)

    @pl.when(pi == pl.num_programs(1) - 1)
    def _emit():
        _paged_flash_emit(o_ref, acc_ref, l_ref)


def paged_flash_attention(q, k_pool, v_pool, block_table, pos, *, n_head,
                          page_size, sm_scale=None, k_scales=None,
                          v_scales=None, interpret=None):
    """Paged attention over the KV pool without materializing the gathered
    context. q is [rows, n_head*d]; block_table is [rows, P] (decode — one
    page list per query row) or [P] (chunked prefill — one list shared by
    all rows); pos[r] bounds row r's live context (attends 0..pos
    inclusive; pos < 0 means fully masked and emits zeros). Returns
    [rows, n_head*d] in q's dtype with f32 accumulation — bit-bounded, not
    bit-identical, vs the dense reference (the online softmax reassociates
    the sum).

    k_scales/v_scales (both or neither): the pools hold int8 levels and
    [pool_rows] f32 per-row scales ride along; the kernel dequantizes
    inline on the block-table walk (each page's scale vector chases the
    same table entry as its K/V rows), so dequantized f32 rows exist only
    in VMEM."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows, feat = q.shape
    d = feat // n_head
    scale = float(sm_scale or 0.0) or d**-0.5
    q3 = q.reshape(rows, n_head, d)
    k3 = k_pool.reshape(-1, n_head, d)
    v3 = v_pool.reshape(-1, n_head, d)
    bt = block_table.astype(jnp.int32)
    pos_v = pos.reshape(-1).astype(jnp.int32)
    quant = k_scales is not None
    operands = [bt, pos_v, q3, k3, v3]
    if quant:
        # one f32 scale per pool row, page-structured so a (1, page_size)
        # block can chase the block table like the K/V pages do
        operands += [
            k_scales.reshape(-1, page_size).astype(jnp.float32),
            v_scales.reshape(-1, page_size).astype(jnp.float32),
        ]
    _note_dispatch("paged_flash_int8" if quant else "paged_flash")
    if bt.ndim == 1:
        n_pages = bt.shape[0]
        in_specs = [
            pl.BlockSpec((rows, 1, d), lambda h, p, bt_r, pos_r: (0, h, 0)),
            pl.BlockSpec(
                (page_size, 1, d),
                lambda h, p, bt_r, pos_r: (bt_r[p], h, 0),
            ),
            pl.BlockSpec(
                (page_size, 1, d),
                lambda h, p, bt_r, pos_r: (bt_r[p], h, 0),
            ),
        ]
        if quant:
            in_specs += [
                pl.BlockSpec(
                    (1, page_size), lambda h, p, bt_r, pos_r: (bt_r[p], 0)
                ),
            ] * 2
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n_head, n_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (rows, 1, d), lambda h, p, bt_r, pos_r: (0, h, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((rows, d), jnp.float32),
                pltpu.VMEM((rows, _LANES), jnp.float32),
                pltpu.VMEM((rows, _LANES), jnp.float32),
            ],
        )
        kernel = functools.partial(
            _paged_flash_shared_quant_kernel if quant
            else _paged_flash_shared_kernel,
            page_size=page_size, sm_scale=scale,
        )
    else:
        n_pages = bt.shape[1]
        in_specs = [
            pl.BlockSpec(
                (1, 1, d), lambda s, h, p, bt_r, pos_r: (s, h, 0)
            ),
            pl.BlockSpec(
                (page_size, 1, d),
                lambda s, h, p, bt_r, pos_r: (bt_r[s, p], h, 0),
            ),
            pl.BlockSpec(
                (page_size, 1, d),
                lambda s, h, p, bt_r, pos_r: (bt_r[s, p], h, 0),
            ),
        ]
        if quant:
            in_specs += [
                pl.BlockSpec(
                    (1, page_size),
                    lambda s, h, p, bt_r, pos_r: (bt_r[s, p], 0),
                ),
            ] * 2
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(rows, n_head, n_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, d), lambda s, h, p, bt_r, pos_r: (s, h, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((1, d), jnp.float32),
                pltpu.VMEM((1, _LANES), jnp.float32),
                pltpu.VMEM((1, _LANES), jnp.float32),
            ],
        )
        kernel = functools.partial(
            _paged_flash_decode_quant_kernel if quant
            else _paged_flash_decode_kernel,
            page_size=page_size, sm_scale=scale,
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, n_head, d), q.dtype),
        interpret=interpret,
    )(*operands)
    return out.reshape(rows, feat)


# ---------------------------------------------------------------------------
# fused layer_norm(+residual): forward with one-pass Welford stats, explicit
# backward against the saved Mean/Variance — both f32 math rounded once
# ---------------------------------------------------------------------------

# 128 rows/block swept on chip at d=2048 bf16: 128 rows runs fwd+bwd at
# 412 GB/s effective (the op is bandwidth-bound) vs 397 at 256 rows (VMEM
# pressure starts evicting the double buffer) and 361 at 64 (grid overhead)
_DEF_LN_BLOCK_ROWS = 128
_LN_COL_CHUNK = 512  # Welford merge chunk width (lanes)
# conservative working-set roof: x/r/s/y native tiles + f32 stats temps per
# block must leave room for double buffering in ~16 MiB VMEM
_LN_VMEM_BUDGET = 12 * 1024 * 1024


def _ln_blocks(rows, cols, itemsize):
    """Row-block size for the fused layer_norm kernels, or 0 for shapes the
    kernel declines: the packed (1, rows) stats layout needs rows % 128 == 0
    (the flash lse rule — Mosaic cannot vector-store partial lanes), the
    Welford chunk sweep needs cols % 128 == 0, and the whole (block, cols)
    slab must sit in VMEM."""
    if rows <= 0 or cols <= 0 or rows % _LANES or cols % _LANES:
        return 0
    br = _auto_block(rows, _DEF_LN_BLOCK_ROWS)
    while br > 8 and br * cols * (4 * itemsize + 16) > _LN_VMEM_BUDGET:
        br //= 2
    if not br or br * cols * (4 * itemsize + 16) > _LN_VMEM_BUDGET:
        return 0
    return br


def ln_path_taken(rows, cols, itemsize=4):
    """EXACT mirror of the fused layer_norm pallas-vs-dense decision over the
    (lead, prod(shape[begin_norm_axis:])) view — see gemm_path_taken."""
    return _ln_blocks(rows, cols, itemsize) > 0


def _welford_cols(s32, cols, col_chunk):
    """One-pass Welford over the column axis of an f32 (rows, chunk-multiple)
    value, merging per-chunk moments with the parallel combination — numerics
    match jnp.mean/jnp.var to f32 rounding without the naive sum-of-squares
    cancellation at large |mean|."""
    nc = cols // col_chunk

    def body(ci, carry):
        count, mean, m2 = carry
        blk = jax.lax.dynamic_slice_in_dim(s32, ci * col_chunk, col_chunk, 1)
        bmean = jnp.mean(blk, axis=1)
        bm2 = jnp.sum(jnp.square(blk - bmean[:, None]), axis=1)
        tot = count + col_chunk
        delta = bmean - mean
        mean = mean + delta * (col_chunk / tot)
        m2 = m2 + bm2 + jnp.square(delta) * (count * col_chunk / tot)
        return tot, mean, m2

    rows = s32.shape[0]
    init = (
        jnp.float32(0.0),
        jnp.zeros((rows,), jnp.float32),
        jnp.zeros((rows,), jnp.float32),
    )
    _, mean, m2 = jax.lax.fori_loop(0, nc, body, init)
    return mean, m2 / cols  # biased variance — the layer_norm contract


def _ln_fwd_kernel(x_ref, r_ref, scale_ref, bias_ref, s_ref, y_ref, mean_ref,
                   var_ref, *, eps, col_chunk):
    """One row block: residual add in the INPUT dtype (bit-matching the dense
    elementwise_add it replaces), Welford stats and normalization in f32,
    packed lane-major (1, rows) Mean/Variance residuals (the flash lse
    layout)."""
    ri = pl.program_id(0)
    block_rows, cols = x_ref.shape
    if r_ref is not None:
        s = x_ref[...] + r_ref[...]
        s_ref[...] = s
    else:
        s = x_ref[...]
    s32 = s.astype(jnp.float32)
    mean, var = _welford_cols(s32, cols, col_chunk)
    y = (s32 - mean[:, None]) * jax.lax.rsqrt(var[:, None] + eps)
    y = y * scale_ref[...].astype(jnp.float32) + bias_ref[...].astype(
        jnp.float32
    )
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[0, pl.ds(ri * block_rows, block_rows)] = mean
    var_ref[0, pl.ds(ri * block_rows, block_rows)] = var


def _ln_fwd_no_residual_adapter(kernel, x_ref, scale_ref, bias_ref, y_ref,
                                mean_ref, var_ref):
    kernel(x_ref, None, scale_ref, bias_ref, None, y_ref, mean_ref, var_ref)


def fused_layer_norm(x2, residual2, scale, bias, eps, *, interpret=None):
    """layer_norm(x2 [+ residual2]) over the (rows, cols) view. Returns
    (s, y, mean, var): s = x2 + residual2 in the input dtype (None when no
    residual), y the normalized output in the input dtype, mean/var the f32
    per-row stats (biased variance). scale/bias of None behave as ones/zeros.
    Shapes the kernel declines (ln_path_taken False) fall back to the dense
    f32 form with identical outputs."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows, cols = x2.shape
    scale_row = (
        jnp.ones((1, cols), jnp.float32)
        if scale is None
        else scale.reshape(1, cols)
    )
    bias_row = (
        jnp.zeros((1, cols), jnp.float32)
        if bias is None
        else bias.reshape(1, cols)
    )
    br = _ln_blocks(rows, cols, x2.dtype.itemsize)
    if not br:
        s = None if residual2 is None else x2 + residual2
        base = x2 if s is None else s
        b32 = base.astype(jnp.float32)
        mean = jnp.mean(b32, axis=1)
        var = jnp.var(b32, axis=1)
        y = (b32 - mean[:, None]) * jax.lax.rsqrt(var[:, None] + eps)
        y = y * scale_row.astype(jnp.float32) + bias_row.astype(jnp.float32)
        return s, y.astype(x2.dtype), mean, var
    col_chunk = _auto_block(cols, _LN_COL_CHUNK)
    kernel = functools.partial(
        _ln_fwd_kernel, eps=eps, col_chunk=col_chunk
    )
    row_spec = pl.BlockSpec((br, cols), lambda ri: (ri, 0))
    cvec_spec = pl.BlockSpec((1, cols), lambda ri: (0, 0))
    stat_spec = pl.BlockSpec((1, rows), lambda ri: (0, 0))
    stat_shape = jax.ShapeDtypeStruct((1, rows), jnp.float32)
    if residual2 is None:
        y, mean, var = pl.pallas_call(
            functools.partial(_ln_fwd_no_residual_adapter, kernel),
            grid=(rows // br,),
            in_specs=[row_spec, cvec_spec, cvec_spec],
            out_specs=[row_spec, stat_spec, stat_spec],
            out_shape=[
                jax.ShapeDtypeStruct((rows, cols), x2.dtype),
                stat_shape,
                stat_shape,
            ],
            interpret=interpret,
        )(x2, scale_row, bias_row)
        return None, y, mean.reshape(rows), var.reshape(rows)
    s, y, mean, var = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[row_spec, row_spec, cvec_spec, cvec_spec],
        out_specs=[row_spec, row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), x2.dtype),
            jax.ShapeDtypeStruct((rows, cols), x2.dtype),
            stat_shape,
            stat_shape,
        ],
        interpret=interpret,
    )(x2, residual2, scale_row, bias_row)
    return s, y, mean.reshape(rows), var.reshape(rows)


def _ln_bwd_kernel(x_ref, scale_ref, mean_ref, var_ref, dy_ref, dx_ref,
                   ds_ref, db_ref, *, eps):
    """One row block of the layer_norm backward against the SAVED stats:
    dx = rstd * (dxhat - mean(dxhat) - xhat * mean(dxhat * xhat)) in f32;
    dscale/dbias accumulate across the sequential grid into (1, cols) f32
    output blocks (constant index_map -> the block stays resident)."""
    ri = pl.program_id(0)
    block_rows = x_ref.shape[0]
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    scale = scale_ref[...].astype(jnp.float32)
    mu = mean_ref[0, pl.ds(ri * block_rows, block_rows)]
    var = var_ref[0, pl.ds(ri * block_rows, block_rows)]
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu[:, None]) * rstd[:, None]
    dxh = dy * scale
    c1 = jnp.mean(dxh, axis=1)
    c2 = jnp.mean(dxh * xhat, axis=1)
    dx_ref[...] = (
        rstd[:, None] * (dxh - c1[:, None] - xhat * c2[:, None])
    ).astype(dx_ref.dtype)

    @pl.when(ri == 0)
    def _init():
        ds_ref[...] = jnp.zeros_like(ds_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    ds_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def fused_layer_norm_grad(x2, scale, mean, var, dy2, eps, *, interpret=None):
    """Backward of the fused layer_norm over the (rows, cols) view. Returns
    (dx, dscale, dbias) with dx in x2's dtype and dscale/dbias as (cols,)
    f32 partials (caller casts to the param dtypes). scale of None behaves
    as ones. Declined shapes fall back to a dense f32 form with the same
    formula."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rows, cols = x2.shape
    scale_row = (
        jnp.ones((1, cols), jnp.float32)
        if scale is None
        else scale.reshape(1, cols)
    )
    br = _ln_blocks(rows, cols, x2.dtype.itemsize)
    if not br:
        x32 = x2.astype(jnp.float32)
        dy32 = dy2.astype(jnp.float32)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = (x32 - mean[:, None]) * rstd[:, None]
        dxh = dy32 * scale_row.astype(jnp.float32)
        c1 = jnp.mean(dxh, axis=1)
        c2 = jnp.mean(dxh * xhat, axis=1)
        dx = (rstd[:, None] * (dxh - c1[:, None] - xhat * c2[:, None])).astype(
            x2.dtype
        )
        return dx, jnp.sum(dy32 * xhat, axis=0), jnp.sum(dy32, axis=0)
    row_spec = pl.BlockSpec((br, cols), lambda ri: (ri, 0))
    cvec_spec = pl.BlockSpec((1, cols), lambda ri: (0, 0))
    stat_spec = pl.BlockSpec((1, rows), lambda ri: (0, 0))
    dx, ds, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[row_spec, cvec_spec, stat_spec, stat_spec, row_spec],
        out_specs=[row_spec, cvec_spec, cvec_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), x2.dtype),
            jax.ShapeDtypeStruct((1, cols), jnp.float32),
            jax.ShapeDtypeStruct((1, cols), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale_row, mean.reshape(1, rows), var.reshape(1, rows), dy2)
    return dx, ds.reshape(cols), db.reshape(cols)


# ---------------------------------------------------------------------------
# multi-tensor Adam: one kernel over flattened, chunk-padded param groups —
# f32 master math, outputs rounded to the per-slot storage dtypes (bf16
# moments supported), per-param lr_t selected via scalar-prefetched indices
# ---------------------------------------------------------------------------

_ADAM_CHUNK_ROWS = 256  # 256x128 = 32k elements per grid step


def adam_path_taken(n_params, zero1=False, sharded=False):
    """Mirror of the fused multi-tensor-Adam dispatch decision: the kernel is
    total over shapes (params are chunk-padded), so the only declines are a
    degenerate group and the sharded tiers — ZeRO-1 and rule-sharded
    (FSDP/TP) params — whose per-param GSPMD sharding constraints
    (core_ops._opt_f32) the flattened kernel cannot express."""
    return n_params >= 2 and not zero1 and not sharded


def _multi_adam_kernel(c2p_ref, lrt_ref, p_ref, g_ref, m1_ref, m2_ref,
                       po_ref, m1o_ref, m2o_ref, *, beta1, beta2, eps):
    """One chunk: the EXACT _adam update expressions (core_ops) on the f32
    upcast, rounded to the storage dtypes on write — bit-identical to the
    unfused per-param chain where that chain's math is f32. lr_t (per param,
    bias correction included) rides a scalar-prefetch table indexed by the
    chunk->param map."""
    i = pl.program_id(0)
    lr_t = lrt_ref[c2p_ref[i]]
    g = g_ref[...].astype(jnp.float32)
    m1 = m1_ref[...].astype(jnp.float32)
    m2 = m2_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m1o = beta1 * m1 + (1 - beta1) * g
    m2o = beta2 * m2 + (1 - beta2) * jnp.square(g)
    po = p - lr_t * m1o / (jnp.sqrt(m2o) + eps)
    po_ref[...] = po.astype(po_ref.dtype)
    m1o_ref[...] = m1o.astype(m1o_ref.dtype)
    m2o_ref[...] = m2o.astype(m2o_ref.dtype)


def _pack_rows(arrs, rows_per):
    """Ravel each array, zero-pad to its chunk-aligned row count, and stack
    lane-major — zero pad rows are mathematically inert in the Adam update
    (0 - lr*0/(sqrt(0)+eps) = 0) and sliced off on unpack."""
    flat = []
    for a, r in zip(arrs, rows_per):
        v = a.reshape(-1)
        pad = r * _LANES - v.size
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        flat.append(v.reshape(r, _LANES))
    return jnp.concatenate(flat, axis=0)


def multi_tensor_adam(params, grads, m1s, m2s, lr_ts, beta1, beta2, epsilon,
                      *, interpret=None):
    """Fused Adam over a param group: flatten every (param, grad, m1, m2)
    quadruple into chunk-padded (rows, 128) slabs, run ONE kernel over the
    concatenation, split back. lr_ts are per-param f32 scalars with bias
    correction already applied (lr * sqrt(1-b2^t)/(1-b1^t)). Params must
    share a dtype per slot (the fused lowering groups by dtype). Returns
    (param_outs, m1_outs, m2_outs) in the input storage dtypes."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    chunk = _ADAM_CHUNK_ROWS * _LANES
    sizes = [int(p.size) for p in params]
    rows_per = [-(-s // chunk) * _ADAM_CHUNK_ROWS for s in sizes]
    chunks_per = [r // _ADAM_CHUNK_ROWS for r in rows_per]
    c2p = np.repeat(np.arange(len(params), dtype=np.int32), chunks_per)
    lrt = jnp.stack([jnp.asarray(v, jnp.float32).reshape(()) for v in lr_ts])
    p_cat = _pack_rows(params, rows_per)
    g_cat = _pack_rows(grads, rows_per)
    m1_cat = _pack_rows(m1s, rows_per)
    m2_cat = _pack_rows(m2s, rows_per)
    total_rows = int(p_cat.shape[0])
    blk = pl.BlockSpec(
        (_ADAM_CHUNK_ROWS, _LANES), lambda i, c2p, lrt: (i, 0)
    )
    po, m1o, m2o = pl.pallas_call(
        functools.partial(
            _multi_adam_kernel, beta1=beta1, beta2=beta2, eps=epsilon
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(sum(chunks_per),),
            in_specs=[blk, blk, blk, blk],
            out_specs=[blk, blk, blk],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((total_rows, _LANES), p_cat.dtype),
            jax.ShapeDtypeStruct((total_rows, _LANES), m1_cat.dtype),
            jax.ShapeDtypeStruct((total_rows, _LANES), m2_cat.dtype),
        ],
        interpret=interpret,
    )(jnp.asarray(c2p), lrt, p_cat, g_cat, m1_cat, m2_cat)
    p_outs, m1_outs, m2_outs = [], [], []
    row = 0
    for p, r, size in zip(params, rows_per, sizes):
        sl = slice(row, row + r)
        p_outs.append(po[sl].reshape(-1)[:size].reshape(p.shape))
        m1_outs.append(m1o[sl].reshape(-1)[:size].reshape(p.shape))
        m2_outs.append(m2o[sl].reshape(-1)[:size].reshape(p.shape))
        row += r
    return p_outs, m1_outs, m2_outs


# ---------------------------------------------------------------------------
# fused lowerings: registry.lower_ops hands tagged runs here; every path that
# cannot reproduce the per-op semantics returns False (per-op fallback)
# ---------------------------------------------------------------------------


class _Shape2:
    __slots__ = ("shape", "ndim")

    def __init__(self, shape):
        self.shape = tuple(shape)
        self.ndim = len(self.shape)


def _rules_sharded(ctx, ops):
    """True when the declarative rule engine (ctx.sharding, a
    parallel.sharding_rules.Resolver) places any of the run's operands or
    results on this mesh. The tiled/flattened kernels assume whole local
    tensors — a tp-sharded weight or fsdp-sharded param would be gathered
    around an opaque pallas_call, defeating the placement — so tagged runs
    decline to per-op lowering, where GSPMD partitions op by op."""
    sharding = getattr(ctx, "sharding", None)
    if sharding is None:
        return False
    for op in ops:
        for name in list(op.input_arg_names) + list(op.output_arg_names):
            if name and sharding.rule_spec(name) is not None:
                return True
    return False


def _gemm_chain_views(prod, x, w):
    """2-D (m,k)/(k,n) views of the producer's operands plus the full output
    shape, or None when the op form is outside the kernel's contract."""
    if prod.type in ("mul", "int8_mul"):
        xnc = int(prod.attrs.get("x_num_col_dims", 1))
        ync = int(prod.attrs.get("y_num_col_dims", 1))
        m = int(np.prod(x.shape[:xnc], dtype=np.int64)) if xnc else 1
        kx = x.size // max(m, 1)
        kw = int(np.prod(w.shape[:ync], dtype=np.int64)) if ync else 1
        n = w.size // max(kw, 1)
        out_shape = tuple(x.shape[:xnc]) + tuple(w.shape[ync:])
        split = xnc
    else:  # matmul
        if prod.attrs.get("transpose_X", False) or prod.attrs.get(
            "transpose_Y", False
        ):
            return None
        if float(prod.attrs.get("alpha", 1.0)) != 1.0:
            return None
        if x.ndim != 2 or w.ndim != 2:
            return None
        m, kx = x.shape
        kw, n = w.shape
        out_shape = (m, n)
        split = 1
    if kx != kw or m <= 0 or n <= 0 or kx <= 0:
        return None
    return m, n, kx, out_shape, split


@register_fused("gemm_epilogue")
def _fused_gemm_epilogue(ctx, ops, env):
    """mul|matmul -> elementwise_add [-> act] through gemm_bias_act. The
    intermediate env entries stay live for OTHER consumers: the producer's
    Out is rebuilt as z - bias (grad ops list it as an input but the vjp
    replay never reads its value, so XLA DCEs the subtraction when unused)
    and the add's Out is the kernel's exact pre-activation z (gelu_grad's
    replay input)."""
    if len(ops) not in (2, 3) or ops[0].type not in ("mul", "matmul"):
        return False
    if _rules_sharded(ctx, ops):
        return False
    prod, add = ops[0], ops[1]
    act_op = ops[2] if len(ops) == 3 else None
    if add.type != "elementwise_add":
        return False
    if act_op is not None and act_op.type not in _GEMM_ACT_F32:
        return False
    if (
        add.input("X")[0] != prod.output("Out")[0]
        or (act_op is not None and act_op.input("X")[0] != add.output("Out")[0])
    ):
        return False
    x = env.get(prod.input("X")[0])
    w = env.get(prod.input("Y")[0])
    bias = env.get(add.input("Y")[0])
    if x is None or w is None or bias is None:
        return False
    if x.dtype != w.dtype or not jnp.issubdtype(x.dtype, jnp.floating):
        return False
    views = _gemm_chain_views(prod, x, w)
    if views is None:
        return False
    m, n, k, out_shape, split = views
    if not gemm_path_taken(m, n, k):
        return False
    bview = bcast_y(_Shape2(out_shape), bias, int(add.attrs.get("axis", -1)))
    if any(d != 1 for d in bview.shape[:split]):
        return False  # bias varying over GEMM rows is outside the epilogue
    brow = jnp.broadcast_to(
        bview, (1,) * split + tuple(out_shape[split:])
    ).reshape(1, n)
    z2, y2 = gemm_bias_act(
        x.reshape(m, k), w.reshape(k, n), brow,
        act=act_op.type if act_op is not None else None,
    )
    env[add.output("Out")[0]] = z2.reshape(out_shape)
    env[prod.output("Out")[0]] = (
        z2.astype(jnp.float32) - brow.astype(jnp.float32)
    ).astype(z2.dtype).reshape(out_shape)
    if act_op is not None:
        env[act_op.output("Out")[0]] = y2.reshape(out_shape)
    _note_dispatch("gemm_epilogue")
    return True


@register_fused("gemm_int8")
def _fused_quant_gemm(ctx, ops, env):
    """int8_mul -> fake_dequantize ×2 [-> elementwise_add [-> act]] through
    quant_gemm_bias_act: the two chained per-tensor dequant multiplies
    collapse into ONE combined scale applied to the i32 accumulator, and the
    bias/activation ride the same epilogue — the whole calibrated-int8 dense
    layer is one kernel with one rounding. Intermediate env entries are
    rebuilt algebraically from z (exact inverses of the epilogue, f32) so
    out-of-run consumers stay correct; XLA DCEs them when unused."""
    if len(ops) not in (3, 4, 5) or ops[0].type != "int8_mul":
        return False
    if _rules_sharded(ctx, ops):
        return False
    prod, d1, d2 = ops[0], ops[1], ops[2]
    if (
        d1.type != "fake_dequantize_max_abs"
        or d2.type != "fake_dequantize_max_abs"
        or d1.input("X") != [prod.output("Out")[0]]
        or d2.input("X") != [d1.output("Out")[0]]
    ):
        return False
    add_op = act_op = None
    if len(ops) >= 4:
        add_op = ops[3]
        if add_op.type != "elementwise_add" or add_op.input("X") != [
            d2.output("Out")[0]
        ]:
            return False
    if len(ops) == 5:
        act_op = ops[4]
        if act_op.type not in _GEMM_ACT_F32 or act_op.input("X") != [
            add_op.output("Out")[0]
        ]:
            return False
    x = env.get(prod.input("X")[0])
    w = env.get(prod.input("Y")[0])
    s1 = env.get(d1.input("Scale")[0])
    s2 = env.get(d2.input("Scale")[0])
    if x is None or w is None or s1 is None or s2 is None:
        return False
    if x.dtype != jnp.int8 or w.dtype != jnp.int8:
        return False
    views = _gemm_chain_views(prod, x, w)
    if views is None:
        return False
    m, n, k, out_shape, split = views
    if not quant_gemm_path_taken(m, n, k, x.dtype):
        return False
    r1 = float(d1.attrs.get("max_range", 127.0))
    r2 = float(d2.attrs.get("max_range", 127.0))
    combined = (jnp.reshape(s1, ()) / r1) * (jnp.reshape(s2, ()) / r2)
    brow = None
    if add_op is not None:
        bias = env.get(add_op.input("Y")[0])
        if bias is None:
            return False
        bview = bcast_y(_Shape2(out_shape), bias, int(add_op.attrs.get("axis", -1)))
        if any(d != 1 for d in bview.shape[:split]):
            return False
        brow = jnp.broadcast_to(
            bview, (1,) * split + tuple(out_shape[split:])
        ).reshape(1, n)
    z2, y2 = quant_gemm_bias_act(
        x.reshape(m, k), w.reshape(k, n), combined, brow,
        act=act_op.type if act_op is not None else None,
    )
    z32 = z2.astype(jnp.float32)
    pre = z32 if brow is None else z32 - brow.astype(jnp.float32)
    env[prod.output("Out")[0]] = (pre / combined).reshape(out_shape)
    env[d1.output("Out")[0]] = (
        pre / jnp.maximum(jnp.reshape(s2, ()) / r2, 1e-30)
    ).reshape(out_shape)
    env[d2.output("Out")[0]] = pre.astype(z2.dtype).reshape(out_shape)
    if add_op is not None:
        env[add_op.output("Out")[0]] = z2.reshape(out_shape)
    if act_op is not None:
        env[act_op.output("Out")[0]] = y2.reshape(out_shape)
    return True


def _ln_view(op, x):
    bna = int(op.attrs.get("begin_norm_axis", 1))
    rows = int(np.prod(x.shape[:bna], dtype=np.int64)) if bna else 1
    cols = x.size // max(rows, 1)
    return rows, cols


@register_fused("layer_norm")
def _fused_layer_norm(ctx, ops, env):
    """[elementwise_add ->] layer_norm through fused_layer_norm. The residual
    form requires strictly equal operand shapes (the pre_post_process "dan"
    chain); anything else declines to per-op."""
    ln = ops[-1]
    if ln.type != "layer_norm" or len(ops) > 2:
        return False
    if _rules_sharded(ctx, ops):
        return False
    add = ops[0] if len(ops) == 2 else None
    if add is not None:
        if (
            add.type != "elementwise_add"
            or add.output("Out")[0] != ln.input("X")[0]
        ):
            return False
        xa = env.get(add.input("X")[0])
        ra = env.get(add.input("Y")[0])
        if xa is None or ra is None or xa.shape != ra.shape or xa.dtype != ra.dtype:
            return False
        x_full = xa
        residual_full = ra
    else:
        x_full = env.get(ln.input("X")[0])
        residual_full = None
        if x_full is None:
            return False
    rows, cols = _ln_view(ln, x_full)
    if not ln_path_taken(rows, cols, x_full.dtype.itemsize):
        return False
    # NOT gather_op_inputs: in the residual form, ln's X is the add's Out,
    # which by design has no env entry yet (the fused kernel produces it)
    scale_names = ln.inputs.get("Scale") or []
    bias_names = ln.inputs.get("Bias") or []
    scale = env.get(scale_names[0]) if scale_names else None
    bias = env.get(bias_names[0]) if bias_names else None
    eps = ln.attrs.get("epsilon", 1e-5)
    s2, y2, mean, var = fused_layer_norm(
        x_full.reshape(rows, cols),
        None if residual_full is None else residual_full.reshape(rows, cols),
        scale, bias, eps,
    )
    if add is not None:
        env[add.output("Out")[0]] = s2.reshape(x_full.shape)
    outs = {"Y": [y2.reshape(x_full.shape)], "Mean": [mean], "Variance": [var]}
    scatter_op_outputs(ln, outs, env)
    _note_dispatch("layer_norm")
    return True


@register_fused("layer_norm_grad")
def _fused_layer_norm_grad(ctx, ops, env):
    """layer_norm_grad through the explicit backward kernel against the saved
    Mean/Variance. Declines when someone differentiates through the stats
    themselves (Mean@GRAD / Variance@GRAD cotangents) — the generic
    vjp-replay fallback handles that exotic case."""
    if len(ops) != 1 or ops[0].type != "layer_norm_grad":
        return False
    if _rules_sharded(ctx, ops):
        return False
    op = ops[0]
    ins = gather_op_inputs(op, env)
    if (
        ins.get("Mean@GRAD", [None])[0] is not None
        or ins.get("Variance@GRAD", [None])[0] is not None
    ):
        return False
    x = ins.get("X", [None])[0]
    dy = ins.get("Y@GRAD", [None])[0]
    mean = ins.get("Mean", [None])[0]
    var = ins.get("Variance", [None])[0]
    if x is None or dy is None or mean is None or var is None:
        return False
    rows, cols = _ln_view(op, x)
    if not ln_path_taken(rows, cols, x.dtype.itemsize):
        return False
    scale = ins.get("Scale", [None])[0]
    eps = op.attrs.get("epsilon", 1e-5)
    dx, ds, db = fused_layer_norm_grad(
        x.reshape(rows, cols), scale, mean, var,
        dy.reshape(rows, cols).astype(x.dtype), eps,
    )
    outs = {"X@GRAD": [dx.reshape(x.shape)]}
    if scale is not None and "Scale@GRAD" in op.outputs:
        outs["Scale@GRAD"] = [ds.reshape(scale.shape).astype(scale.dtype)]
    bias = ins.get("Bias", [None])[0]
    if bias is not None and "Bias@GRAD" in op.outputs:
        outs["Bias@GRAD"] = [db.reshape(bias.shape).astype(bias.dtype)]
    scatter_op_outputs(op, outs, env)
    _note_dispatch("layer_norm_grad")
    return True


@register_fused("multi_adam")
def _fused_multi_adam(ctx, ops, env):
    """A contiguous run of dense adam ops through ONE multi_tensor_adam call
    per (param, grad, moment) dtype signature. lr_t (bias correction) is
    computed OUTSIDE the kernel with the exact _adam expressions, so the
    fused update is bit-identical to the per-param f32 chain. The ZeRO-1
    tier declines: _opt_f32's per-param GSPMD reduce-scatter/all-gather
    constraints don't survive flattening. Likewise rule-sharded (FSDP/TP)
    params — their storage layouts are per-tensor."""
    if ctx.zero1_axis is not None and ctx.mesh is not None:
        return False
    if _rules_sharded(ctx, ops):
        return False
    if len(ops) < 2 or any(op.type != "adam" for op in ops):
        return False
    a0 = ops[0].attrs
    b1 = a0.get("beta1", 0.9)
    b2 = a0.get("beta2", 0.999)
    eps = a0.get("epsilon", 1e-8)
    recs = []
    for op in ops:
        a = op.attrs
        if (
            a.get("beta1", 0.9) != b1
            or a.get("beta2", 0.999) != b2
            or a.get("epsilon", 1e-8) != eps
        ):
            return False
        ins = gather_op_inputs(op, env)
        vals = [
            ins.get(s, [None])[0]
            for s in (
                "Param", "Grad", "Moment1", "Moment2",
                "LearningRate", "Beta1Pow", "Beta2Pow",
            )
        ]
        if any(v is None for v in vals):
            return False
        recs.append((op, vals))
    if not adam_path_taken(len(recs), zero1=False):
        return False
    by_dtype = {}
    for op, (p, g, m1, m2, lr, b1p, b2p) in recs:
        lr_t = (
            lr.reshape(()).astype(jnp.float32)
            * jnp.sqrt(1 - b2p.astype(jnp.float32).reshape(()))
            / (1 - b1p.astype(jnp.float32).reshape(()))
        )
        key = (str(p.dtype), str(g.dtype), str(m1.dtype), str(m2.dtype))
        by_dtype.setdefault(key, []).append((op, p, g, m1, m2, lr_t))
    for group in by_dtype.values():
        p_outs, m1_outs, m2_outs = multi_tensor_adam(
            [r[1] for r in group],
            [r[2] for r in group],
            [r[3] for r in group],
            [r[4] for r in group],
            [r[5] for r in group],
            b1, b2, eps,
        )
        for (op, *_), po, m1o, m2o in zip(group, p_outs, m1_outs, m2_outs):
            scatter_op_outputs(
                op,
                {"ParamOut": [po], "Moment1Out": [m1o], "Moment2Out": [m2o]},
                env,
            )
    _note_dispatch("multi_adam")
    return True
