"""Pallas TPU kernels — the hand-tuned hot-op tier.

Reference analog: operators/math/jit_kernel.h:33-79 + jit_gen.h:41 — the
reference JIT-assembles x86 vector kernels (Xbyak) where the compiler's
codegen wasn't enough; on TPU that role belongs to Pallas kernels lowered
onto MXU/VPU tiles (SURVEY.md §7.9 perf closure).

Kernels: blockwise flash attention forward (online-softmax over KV blocks,
saving only the per-row logsumexp) and a fused flash-attention-2 style
backward — one kernel per K block computing dK, dV, and dQ partials, so the
score matrix and dO·Vᵀ are built once instead of twice (the classic
two-kernel split recomputes both; measured 2.4 -> 1.56 ms per fwd+grad at
t=1024 on chip). Long-context shapes stream the non-resident side through
the grid (separate dQ / dKV kernels there, where VMEM residency is the
binding constraint, not flop count). Ragged tile shapes fall back to the
dense form in both directions (a trace-time decision).

On non-TPU backends (the CPU test mesh) the kernel runs in Pallas interpret
mode — same code path, no Mosaic compile — keeping tests hermetic.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register

__all__ = ["flash_attention", "flash_tiles_ok", "flash_path_taken"]

_DEF_BLOCK_Q = 1024
_DEF_BLOCK_K = 1024
_DEF_BLOCK_Q_CAUSAL = 512
_DEF_BLOCK_K_CAUSAL = 512  # smaller K stream keeps the causal chunk-skip live
# streamed (long-context) tier optimum, swept at t=16384 on chip: (1024,1024)
# runs 100/124 TF/s eff fwd (causal/not) vs 51/63 at (512,512); same ranking
# for the backward (97/121 vs 67/90); 2048 tiles overflow VMEM
_DEF_STREAM_BLOCK = 1024
_LANES = 128  # Mosaic minimum tile width for the residual tensors


def _auto_block(t, target):
    """Largest power-of-two-scaled block ≤ target that divides t, else t
    itself when a single whole tile fits. Returns 0 for ragged shapes (the
    caller falls back to the dense form). Measured on chip (t=1024, d=128,
    b*h=128): (block_q, block_k) = (128,128) runs the forward at 21 TF/s,
    (512,1024) at 122 TF/s — the MXU needs the bigger s=(block_q, block_k)
    tiles to amortize; small defaults were the single biggest attention
    sink. Causal sweeps put (512,512) first (46→56 TF/s effective over
    (512,1024): one whole-t K block can't skip masked chunks); the backward
    shares the forward's optimum (fwd+bwd grad 2.31 ms = 104 TF/s at
    (512,1024) vs 2.68 at (512,512))."""
    c = target
    while c >= 128:
        if t % c == 0:
            return c
        c //= 2
    return t if t <= target else 0


def _resolve_blocks(block_q, block_k, causal):
    # r05 on-chip sweep (t=1024, d=128, bh=128, fused bwd): non-causal
    # (1024,1024) runs fwd+grad at 1.60 ms vs 1.77 at (512,1024); causal
    # keeps (512,512) (1.84 ms; one whole-t K block can't skip masked chunks)
    return (
        block_q or (_DEF_BLOCK_Q_CAUSAL if causal else _DEF_BLOCK_Q),
        block_k or (_DEF_BLOCK_K_CAUSAL if causal else _DEF_BLOCK_K),
    )


def _resident_ok(t, d, itemsize):
    """Whether a whole-(t, d) K and V (or q/do/lse/delta) residency fits the
    ~16 MiB VMEM budget with room for tiles and double-buffering. Calibrated
    on chip: t=8192, d=128, bf16 (4 MiB for K+V) compiles and runs; t=16384
    overflows ("Scoped allocation ... exceeded scoped vmem limit"). Beyond
    this the streamed kernels below tile the long side through the grid."""
    return t * d * itemsize * 2 <= 4 * 1024 * 1024


def _attention_reference(q, k, v, causal, sm_scale):
    """Dense XLA attention — the numerics contract and the vjp source."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k, causal,
                  sm_scale, q_block_idx_axis, t_q_total, lse_packed=True):
    """One (batch*head, q_block) program: stream KV blocks with the online
    softmax recurrence (m = running max, l = running sum, acc = running PV)."""
    qi = pl.program_id(q_block_idx_axis)
    # operands stay in their native dtype (bf16 on the train path): the MXU
    # multiplies bf16 pairs at full rate and accumulates f32 via
    # preferred_element_type — upcasting to f32 FIRST forces the multi-pass
    # f32 MXU emulation at a fraction of peak (measured: the whole fwd
    # kernel 131 -> 178 TF/s from this change alone)
    q = q_ref[...]  # (block_q, d)
    block_q = q.shape[0]
    t_k = k_ref.shape[0]
    nk = pl.cdiv(t_k, block_k)

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (block_q, block_k)
        if causal:
            # bottom-right alignment (same contract as _attention_reference's
            # tril(k=tk-tq)): query row i may see keys up to i + (tk - tq)
            offset = t_k - t_q_total
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        # -inf rows (fully masked so far) must not poison the rescale
        alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        # p rounds to v's dtype for the PV dot — the same rounding the dense
        # XLA chain applies (probs.astype(q.dtype) in _attention_reference)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    d = q.shape[1]
    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q,), -jnp.inf, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    if causal:
        # only KV blocks reaching this q block's last visible key contribute
        last_key = qi * block_q + block_q - 1 + (t_k - t_q_total)
        nk_needed = jnp.clip((last_key + block_k) // block_k, 0, nk)
    else:
        nk_needed = nk
    acc, m, l = jax.lax.fori_loop(0, nk_needed, body, init)
    o_ref[...] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        # logsumexp residual for the flash backward, PACKED as a (1, t_q)
        # lane-major row per (b*h) — the earlier 128-lane broadcast layout
        # cost ~67 MB of HBM write+read per bench attention layer where
        # this is ~0.5 MB (the relayout from the row-reduction's sublane
        # vector is a cheap in-register transpose). Fully-masked rows get
        # a finite sentinel; their p = exp(-inf - lse) is 0 either way.
        lse = jnp.where(m == -jnp.inf, 0.0, m + jnp.log(jnp.maximum(l, 1e-20)))
        if lse_packed:
            lse_ref[0, pl.ds(qi * block_q, block_q)] = lse.astype(lse_ref.dtype)
        else:
            # sub-128-lane t: Mosaic cannot vector-store partial lanes, so
            # tiny shapes keep the 128-lane broadcast residual layout
            lse_ref[...] = jnp.broadcast_to(
                lse[:, None], lse_ref.shape
            ).astype(lse_ref.dtype)


def _flash_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                           m_ref, l_ref, *, causal, sm_scale, t_q_total,
                           t_k_total, with_lse, lse_packed=True):
    """Long-context forward: grid (bh, q_blocks, k_blocks) with K/V streamed
    through the innermost grid dim, so VMEM holds one (block_q, d) query tile
    plus one (block_k, d) K/V tile regardless of t — the whole-KV-resident
    kernel above overflows VMEM past ~8k tokens (see _resident_ok). The
    online-softmax state (acc, m, l) lives in f32 VMEM scratch across the
    k-block sweep; the output tile is written on the last k step."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        offset = t_k_total - t_q_total
        needed = ki * block_k <= qi * block_q + block_q - 1 + offset
    else:
        needed = qi >= 0  # trivially true, keeps pl.when uniform

    @pl.when(needed)
    def _step():
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + (t_k_total - t_q_total) >= k_pos, s, -jnp.inf)
        m_prev = m_ref[..., 0]
        l_prev = l_ref[..., 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        m = m_ref[..., 0]
        l = l_ref[..., 0]
        o_ref[...] = (acc_ref[...] / jnp.maximum(l, 1e-20)[:, None]).astype(
            o_ref.dtype
        )
        if with_lse:
            lse = jnp.where(m == -jnp.inf, 0.0, m + jnp.log(jnp.maximum(l, 1e-20)))
            if lse_packed:
                lse_ref[0, pl.ds(qi * block_q, block_q)] = lse.astype(
                    lse_ref.dtype
                )
            else:  # sub-128-lane t_q: see _flash_kernel's note
                lse_ref[...] = jnp.broadcast_to(
                    lse[:, None], lse_ref.shape
                ).astype(lse_ref.dtype)


def _flash_forward_streamed(q3, k3, v3, causal, sm_scale, block_q, block_k,
                            interpret, with_lse, out_dtype):
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    grid = (bh, tq // block_q, tk // block_k)
    packed = tq % _LANES == 0
    out_shapes = [jax.ShapeDtypeStruct((bh, tq, d), out_dtype)]
    out_specs = [pl.BlockSpec((None, block_q, d), lambda bh, qi, ki: (bh, qi, 0))]
    if with_lse:
        if packed:
            out_shapes.append(jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32))
            out_specs.append(
                pl.BlockSpec((None, 1, tq), lambda bh, qi, ki: (bh, 0, 0))
            )
        else:
            out_shapes.append(
                jax.ShapeDtypeStruct((bh, tq, _LANES), jnp.float32)
            )
            out_specs.append(
                pl.BlockSpec(
                    (None, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0)
                )
            )
    kernel = functools.partial(
        _flash_kernel_streamed,
        causal=causal,
        sm_scale=sm_scale,
        t_q_total=tq,
        t_k_total=tk,
        with_lse=with_lse,
        lse_packed=packed,
    )
    if not with_lse:
        kernel = functools.partial(_no_lse_adapter, kernel)
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shapes if with_lse else out_shapes[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return res


def _no_lse_adapter(kernel, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
    kernel(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref)


def flash_tiles_ok(t, block=None):
    """Conservative symmetric predicate for callers that REQUIRE the Pallas
    path on a square t (the flash ring, whose merge needs the lse the dense
    fallback doesn't produce). It gates on the TIGHTEST block target across
    causal/non-causal and q/k sides (the causal 512 targets) — if it passes,
    _flash_forward takes the Pallas path for both directions in either
    mode."""
    if t <= 0:
        return False
    tightest = min(_DEF_BLOCK_Q, _DEF_BLOCK_K,
                   _DEF_BLOCK_Q_CAUSAL, _DEF_BLOCK_K_CAUSAL)
    return _auto_block(t, block or tightest) > 0


def flash_path_taken(tq, tk, causal=False, block_q=None, block_k=None):
    """EXACT mirror of _flash_forward's pallas-vs-dense decision, for code
    that must predict it from static shapes (layers.flash_attention decides
    whether to declare the Lse output with this — a mismatch would either
    dangle a declared var or silently drop the saved residual and force the
    dense recompute-vjp backward)."""
    if tq <= 0 or tk <= 0:
        return False
    bq, bk = _resolve_blocks(block_q, block_k, causal)
    return _auto_block(tq, bq) > 0 and _auto_block(tk, bk) > 0


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                   with_lse=False):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    raw_bq, raw_bk = block_q, block_k
    block_q, block_k = _resolve_blocks(block_q, block_k, causal)
    block_q = _auto_block(tq, block_q)
    block_k = _auto_block(tk, block_k)
    if not (block_q and block_k):
        # ragged tails: fall back to the dense form (shapes are static, so
        # this is a trace-time decision, not a runtime branch)
        out = _attention_reference(q, k, v, causal, sm_scale)
        return (out, None) if with_lse else out
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    if not _resident_ok(tk, d, k.dtype.itemsize):
        # long-context tier: stream K/V through the grid instead of holding
        # them whole in VMEM; the streamed optimum is larger tiles (the gate
        # above already passed, and the stream targets only widen it)
        res = _flash_forward_streamed(
            q3, k3, v3, causal, sm_scale,
            _auto_block(tq, raw_bq or _DEF_STREAM_BLOCK),
            _auto_block(tk, raw_bk or _DEF_STREAM_BLOCK),
            interpret, with_lse, q.dtype,
        )
        if with_lse:
            out, lse = res
            if tq % _LANES:
                lse = lse[..., 0]
            return out.reshape(b, h, tq, d), lse.reshape(b, h, tq)
        return res.reshape(b, h, tq, d)
    if max(tq, tk) >= 4096:
        # same VMEM clamp as the fused backward: the (1024, block_k) f32
        # score/probability temporaries + resident K/V slabs overflow VMEM
        # once EITHER side reaches t=4096 (the slabs scale with tk, the
        # temporaries with block_q*block_k — compile-checked on chip,
        # including asymmetric tq=1024/tk=4096); 512 holds through 8192
        block_q = min(block_q, 512)
    grid = (b * h, tq // block_q)
    packed = tq % _LANES == 0  # see _flash_kernel's sub-128-lane note
    out_shapes = [jax.ShapeDtypeStruct((b * h, tq, d), q.dtype)]
    out_specs = [pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0))]
    if with_lse:
        if packed:
            out_shapes.append(jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32))
            out_specs.append(pl.BlockSpec((None, 1, tq), lambda bh, qi: (bh, 0, 0)))
        else:
            out_shapes.append(
                jax.ShapeDtypeStruct((b * h, tq, _LANES), jnp.float32)
            )
            out_specs.append(
                pl.BlockSpec((None, block_q, _LANES), lambda bh, qi: (bh, qi, 0))
            )
    res = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            q_block_idx_axis=1,
            t_q_total=tq,
            lse_packed=packed,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shapes if with_lse else out_shapes[0],
        interpret=interpret,
    )(q3, k3, v3)
    if with_lse:
        out, lse = res
        if not packed:
            lse = lse[..., 0]
        return out.reshape(b, h, tq, d), lse.reshape(b, h, tq)
    return res.reshape(b, h, tq, d)




# ---------------------------------------------------------------------------
# flash backward (flash-attention-2 style): dQ in one kernel over q blocks,
# dK/dV in another over k blocks, both streaming the opposite side and using
# the saved logsumexp L plus D = rowsum(dO * O)
# ---------------------------------------------------------------------------


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                            dk_ref, dv_ref, dqp_ref, *, block_q, causal,
                            sm_scale, t_q_total, lse_packed=True):
    """Fused resident backward: one (bh, k_block) program computes dK and dV
    for its K block AND this K block's partial contribution to every dQ row
    (summed over k blocks by XLA outside). The two-kernel form recomputes the
    score matrix s and dp = dO·Vᵀ in BOTH kernels — 7 matmul-units per
    backward vs the 5 this kernel executes (s, dp, dV, dK, dQ-partial), a
    28%% flop cut on the exact tier the MFU bench runs (measured on chip:
    fwd+grad 2.42 -> 1.87 ms at t=1024 bh=128 non-causal)."""
    ki = pl.program_id(1)
    k_blk = k_ref[...]  # (block_k, d)
    v_blk = v_ref[...]
    block_k = k_blk.shape[0]
    t_k_total = pl.num_programs(1) * block_k
    offset = t_k_total - t_q_total  # bottom-right causal alignment
    t_q = q_ref.shape[0]
    nq = pl.cdiv(t_q, block_q)

    dqp_ref[...] = jnp.zeros_like(dqp_ref)  # skipped causal rows stay 0

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :]
        if lse_packed:
            lse = lse_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        else:
            lse = lse_ref[pl.ds(qi * block_q, block_q), 0].astype(jnp.float32)
        # delta = rowsum(dO * O) computed here from the saved forward output
        # rather than as an XLA prologue: the prologue form writes + re-reads
        # a 128-lane-broadcast f32 tensor per layer (~134 MB of HBM traffic)
        # where this is a VPU rowsum over tiles already resident
        o_blk = o_ref[pl.ds(qi * block_q, block_q), :]
        delta = jnp.sum(
            do_blk.astype(jnp.float32) * o_blk.astype(jnp.float32), axis=1
        )
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q_blk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dqp_ref[pl.ds(qi * block_q, block_q), :] = jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dqp_ref.dtype)
        return dk, dv

    if causal:
        first_q_row = ki * block_k - offset
        q_start = jnp.clip(first_q_row // block_q, 0, nq)
    else:
        q_start = 0
    d = k_blk.shape[1]
    dk, dv = jax.lax.fori_loop(
        q_start,
        nq,
        body,
        (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32)),
    )
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dq_ref, dq_acc, *, causal, sm_scale, t_q_total,
                           t_k_total, lse_packed=True):
    """Streamed dQ: grid (bh, q_blocks, k_blocks); K/V tiles ride the inner
    grid dim, dQ accumulates in f32 scratch and lands on the last k step."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        offset = t_k_total - t_q_total
        needed = ki * block_k <= qi * block_q + block_q - 1 + offset
    else:
        needed = qi >= 0

    @pl.when(needed)
    def _step():
        block_q_ = q_ref.shape[0]
        q = q_ref[...]
        do = do_ref[...]
        if lse_packed:
            lse = lse_ref[0, pl.ds(qi * block_q_, block_q_)].astype(jnp.float32)
            delta = delta_ref[0, pl.ds(qi * block_q_, block_q_)].astype(
                jnp.float32
            )
        else:  # per-q-block 128-lane broadcast layout (sub-128-lane t_q)
            lse = lse_ref[..., 0].astype(jnp.float32)
            delta = delta_ref[..., 0].astype(jnp.float32)
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + (t_k_total - t_q_total) >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
        dq_acc[...] = dq_acc[...] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dk_ref, dv_ref, dk_acc, dv_acc, *, causal,
                            sm_scale, t_q_total, t_k_total, lse_packed=True):
    """Streamed dK/dV: grid (bh, k_blocks, q_blocks); Q/dO/lse/delta tiles
    ride the inner grid dim, dK/dV accumulate in f32 scratch."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    offset = t_k_total - t_q_total
    if causal:
        # q rows before this k block's first key see nothing of it
        needed = qi * block_q + block_q - 1 + offset >= ki * block_k
    else:
        needed = qi >= 0

    @pl.when(needed)
    def _step():
        block_q_ = q_ref.shape[0]
        q_blk = q_ref[...]
        do_blk = do_ref[...]
        if lse_packed:
            lse = lse_ref[0, pl.ds(qi * block_q_, block_q_)].astype(jnp.float32)
            delta = delta_ref[0, pl.ds(qi * block_q_, block_q_)].astype(
                jnp.float32
            )
        else:
            lse = lse_ref[..., 0].astype(jnp.float32)
            delta = delta_ref[..., 0].astype(jnp.float32)
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q_blk.dtype)
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward_streamed(q3, k3, v3, do3, lse3, delta, causal, sm_scale,
                             block_q, block_k, interpret, out_dtypes):
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    packed = tq % _LANES == 0  # lse3/delta arrive in the matching layout
    q_spec = pl.BlockSpec((None, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    k_spec = pl.BlockSpec((None, block_k, d), lambda bh, qi, ki: (bh, ki, 0))
    lane_q = (
        pl.BlockSpec((None, 1, tq), lambda bh, qi, ki: (bh, 0, 0))
        if packed
        else pl.BlockSpec((None, block_q, _LANES), lambda bh, qi, ki: (bh, qi, 0))
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_streamed,
            causal=causal, sm_scale=sm_scale, t_q_total=tq, t_k_total=tk,
            lse_packed=packed,
        ),
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, lane_q, lane_q],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), out_dtypes[0]),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta)

    kq_spec = pl.BlockSpec((None, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    kk_spec = pl.BlockSpec((None, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    klane_q = (
        pl.BlockSpec((None, 1, tq), lambda bh, ki, qi: (bh, 0, 0))
        if packed
        else pl.BlockSpec((None, block_q, _LANES), lambda bh, ki, qi: (bh, qi, 0))
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_streamed,
            causal=causal, sm_scale=sm_scale, t_q_total=tq, t_k_total=tk,
            lse_packed=packed,
        ),
        grid=(bh, tk // block_k, tq // block_q),
        in_specs=[kq_spec, kk_spec, kk_spec, kq_spec, klane_q, klane_q],
        out_specs=[kk_spec, kk_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), out_dtypes[1]),
            jax.ShapeDtypeStruct((bh, tk, d), out_dtypes[2]),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta)
    return dq, dk, dv


def _flash_backward(q, k, v, out, lse, dout, causal, sm_scale, block_q,
                    block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    raw_bq, raw_bk = block_q, block_k
    block_q, block_k = _resolve_blocks(block_q, block_k, causal)
    block_q = _auto_block(tq, block_q)
    block_k = _auto_block(tk, block_k)
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    do3 = dout.reshape(b * h, tq, d)
    packed = tq % _LANES == 0  # matches the forward's residual layout rule
    if packed:
        lse3 = lse.reshape(b * h, 1, tq)
    else:
        lse3 = jnp.broadcast_to(
            lse.reshape(b * h, tq)[..., None], (b * h, tq, _LANES)
        )

    # the fused kernel needs whole-side VMEM residency (breaks past ~8k
    # tokens) and materializes an (nk, tq, d) dQ-partials HBM temporary —
    # bounded to <=2x dQ by the nk cap here; everything bigger takes the
    # grid-streamed two-kernel tier (any t, O(t) memory)
    if tk // block_k > 2 or not (
        _resident_ok(tk, d, k.dtype.itemsize)
        and _resident_ok(tq, d, q.dtype.itemsize)
    ):
        delta = jnp.sum(
            dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        )
        if packed:
            delta = delta.reshape(b * h, 1, tq)
        else:  # must mirror lse3's layout — the kernels' specs follow it
            delta = jnp.broadcast_to(
                delta.reshape(b * h, tq)[..., None], (b * h, tq, _LANES)
            )
        dq, dk, dv = _flash_backward_streamed(
            q3, k3, v3, do3, lse3, delta, causal, sm_scale,
            _auto_block(tq, raw_bq or _DEF_STREAM_BLOCK),
            _auto_block(tk, raw_bk or _DEF_STREAM_BLOCK),
            interpret, (q.dtype, k.dtype, v.dtype),
        )
        return (
            dq.reshape(b, h, tq, d),
            dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d),
        )

    if max(tq, tk) >= 4096:
        # the fused kernel's f32 score/probability temporaries at
        # block_q=1024 overflow VMEM once the resident slabs (q/do/o with
        # tq, K/V with tk) reach t=4096 (compile-checked on chip); 512
        # holds through t=8192
        block_q = min(block_q, 512)
    nk = tk // block_k
    dk, dv, dqp = pl.pallas_call(
        functools.partial(
            _flash_bwd_fused_kernel,
            block_q=block_q,
            causal=causal,
            sm_scale=sm_scale,
            t_q_total=tq,
            lse_packed=packed,
        ),
        grid=(b * h, nk),
        in_specs=[
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            (
                pl.BlockSpec((None, 1, tq), lambda bh, ki: (bh, 0, 0))
                if packed
                else pl.BlockSpec((None, tq, _LANES), lambda bh, ki: (bh, 0, 0))
            ),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, None, tq, d), lambda bh, ki: (bh, ki, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
            # dQ partials, one slab per k block, in q's dtype: each partial
            # is already f32-accumulated inside its dot; the cross-block sum
            # over nk<=2 terms (the tier gate above routes tk//block_k > 2
            # to the streamed path) loses nothing the final bf16 cast keeps
            jax.ShapeDtypeStruct((b * h, nk, tq, d), q.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, out.reshape(b * h, tq, d), lse3)
    dq = (
        dqp[:, 0]
        if nk == 1
        else jnp.sum(dqp, axis=1, dtype=jnp.float32).astype(q.dtype)
    )

    return (
        dq.reshape(b, h, tq, d),
        dk.reshape(b, h, tk, d),
        dv.reshape(b, h, tk, d),
    )


def _resolve_defaults(q, sm_scale, interpret):
    """Single source of the defaulting rule: forward, _fwd and _bwd must
    agree or a custom_vjp would silently produce wrong gradients."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return sm_scale, interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    block_q=None,
    block_k=None,
    interpret=None,
):
    """softmax(QKᵀ·scale [causal-masked]) V over (b, h, t, d) tensors.

    block_q/block_k of None pick tuned per-direction defaults adapted to the
    sequence length (_auto_block); explicit values act as upper-bound targets.
    """
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    res = _flash_forward(
        q, k, v, causal, sm_scale, block_q, block_k, interpret, with_lse=True
    )
    out, lse = res
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    if lse is None:
        # ragged-tail fallback: dense recompute-vjp (same trace-time decision
        # as the forward fallback)
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale), q, k, v
        )
        return vjp(dout)
    return _flash_backward(
        q, k, v, out, lse, dout, causal, sm_scale, block_q, block_k, interpret
    )


flash_attention.defvjp(_fwd, _bwd)


@register("flash_attention")
def _flash_attention_op(ctx, ins, attrs):
    """Graph-op form: Q/K/V (b, h, t, d) → Out (+ Lse residual). The
    transformer layers emit this in place of the matmul+softmax+matmul chain.

    The logsumexp residual is emitted as a side output so the explicit
    flash_attention_grad below can run the flash backward against the SAVED
    forward — without it, the generic vjp-derived grad re-traces the forward
    inside jax.vjp, and since the duplicate is a pallas custom-call with a
    different output arity, XLA CSE cannot deduplicate it (one extra forward
    kernel run per attention block per step, measured on chip)."""
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    causal = bool(attrs.get("causal", False))
    sm_scale, interpret = _resolve_defaults(q, attrs.get("sm_scale"), None)
    out, lse = _flash_forward(
        q, k, v, causal, sm_scale, None, None, interpret, with_lse=True
    )
    res = {"Out": [out]}
    if lse is not None:
        res["Lse"] = [lse]
    return res


@register("flash_attention_grad", no_grad=True)
def _flash_attention_grad_op(ctx, ins, attrs):
    """Explicit grad: flash backward kernels against the saved Out/Lse.
    Falls back to the dense recompute-vjp when the forward took the dense
    path (no Lse in the program — ragged tiles)."""
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    (dout,) = ins["Out@GRAD"]
    causal = bool(attrs.get("causal", False))
    sm_scale, interpret = _resolve_defaults(q, attrs.get("sm_scale"), None)
    lse = ins.get("Lse", [None])[0]
    if lse is None:
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale),
            q, k, v,
        )
        dq, dk, dv = vjp(dout.astype(q.dtype))
    else:
        (out,) = ins["Out"]
        dq, dk, dv = _flash_backward(
            q, k, v, out, lse, dout, causal, sm_scale, None, None, interpret
        )
    return {"Q@GRAD": [dq], "K@GRAD": [dk], "V@GRAD": [dv]}
