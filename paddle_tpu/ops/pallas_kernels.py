"""Pallas TPU kernels — the hand-tuned hot-op tier.

Reference analog: operators/math/jit_kernel.h:33-79 + jit_gen.h:41 — the
reference JIT-assembles x86 vector kernels (Xbyak) where the compiler's
codegen wasn't enough; on TPU that role belongs to Pallas kernels lowered
onto MXU/VPU tiles (SURVEY.md §7.9 perf closure).

First kernel: blockwise flash attention (online-softmax over KV blocks) —
the transformer hot path. O(t) VMEM instead of the O(t²) score matrix,
fusing QKᵀ → masked online softmax → PV into one kernel. Backward uses the
standard recompute-vjp over the mathematically identical dense form (the
flash-attention-2 trick of saving only the logsumexp), so autodiff works
through the op while the forward runs the Pallas kernel.

On non-TPU backends (the CPU test mesh) the kernel runs in Pallas interpret
mode — same code path, no Mosaic compile — keeping tests hermetic.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .registry import register

__all__ = ["flash_attention"]

_DEF_BLOCK_Q = 128
_DEF_BLOCK_K = 128


def _attention_reference(q, k, v, causal, sm_scale):
    """Dense XLA attention — the numerics contract and the vjp source."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, sm_scale,
                  q_block_idx_axis, t_q_total):
    """One (batch*head, q_block) program: stream KV blocks with the online
    softmax recurrence (m = running max, l = running sum, acc = running PV)."""
    qi = pl.program_id(q_block_idx_axis)
    q = q_ref[...].astype(jnp.float32)  # (block_q, d)
    block_q = q.shape[0]
    t_k = k_ref.shape[0]
    nk = pl.cdiv(t_k, block_k)

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (block_q, block_k)
        if causal:
            # bottom-right alignment (same contract as _attention_reference's
            # tril(k=tk-tq)): query row i may see keys up to i + (tk - tq)
            offset = t_k - t_q_total
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        # -inf rows (fully masked so far) must not poison the rescale
        alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    d = q.shape[1]
    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q,), -jnp.inf, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    if causal:
        # only KV blocks reaching this q block's last visible key contribute
        last_key = qi * block_q + block_q - 1 + (t_k - t_q_total)
        nk_needed = jnp.clip((last_key + block_k) // block_k, 0, nk)
    else:
        nk_needed = nk
    acc, m, l = jax.lax.fori_loop(0, nk_needed, body, init)
    o_ref[...] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        # ragged tails: fall back to the dense form (shapes are static, so
        # this is a trace-time decision, not a runtime branch)
        return _attention_reference(q, k, v, causal, sm_scale)
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    grid = (b * h, tq // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            q_block_idx_axis=1,
            t_q_total=tq,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, tq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    block_q=_DEF_BLOCK_Q,
    block_k=_DEF_BLOCK_K,
    interpret=None,
):
    """softmax(QKᵀ·scale [causal-masked]) V over (b, h, t, d) tensors."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, dout):
    q, k, v = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    # recompute-vjp through the dense form: identical math, O(t²) only in
    # the backward (flash backward kernels are a later perf-closure step)
    _, vjp = jax.vjp(lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale), q, k, v)
    return vjp(dout)


flash_attention.defvjp(_fwd, _bwd)


@register("flash_attention")
def _flash_attention_op(ctx, ins, attrs):
    """Graph-op form: Q/K/V (b, h, t, d) → Out. The transformer layers can
    emit this in place of the matmul+softmax+matmul chain."""
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    return {
        "Out": [
            flash_attention(
                q,
                k,
                v,
                bool(attrs.get("causal", False)),
                attrs.get("sm_scale"),
            )
        ]
    }
