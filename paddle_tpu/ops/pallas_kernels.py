"""Pallas TPU kernels — the hand-tuned hot-op tier.

Reference analog: operators/math/jit_kernel.h:33-79 + jit_gen.h:41 — the
reference JIT-assembles x86 vector kernels (Xbyak) where the compiler's
codegen wasn't enough; on TPU that role belongs to Pallas kernels lowered
onto MXU/VPU tiles (SURVEY.md §7.9 perf closure).

Kernels: blockwise flash attention forward (online-softmax over KV blocks,
saving only the per-row logsumexp) and the flash-attention-2 style backward
(dQ streamed over K blocks; dK/dV streamed over Q blocks) — the transformer
hot path with O(t) attention memory end to end, ~1.4-2x XLA's dense chain at
t=4096 bf16 on chip. Ragged tile shapes fall back to the dense form in both
directions (a trace-time decision).

On non-TPU backends (the CPU test mesh) the kernel runs in Pallas interpret
mode — same code path, no Mosaic compile — keeping tests hermetic.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .registry import register

__all__ = ["flash_attention", "flash_tiles_ok"]

_DEF_BLOCK_Q = 128
_DEF_BLOCK_K = 128
_LANES = 128  # Mosaic minimum tile width for the residual tensors


def _attention_reference(q, k, v, causal, sm_scale):
    """Dense XLA attention — the numerics contract and the vjp source."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k, causal,
                  sm_scale, q_block_idx_axis, t_q_total):
    """One (batch*head, q_block) program: stream KV blocks with the online
    softmax recurrence (m = running max, l = running sum, acc = running PV)."""
    qi = pl.program_id(q_block_idx_axis)
    q = q_ref[...].astype(jnp.float32)  # (block_q, d)
    block_q = q.shape[0]
    t_k = k_ref.shape[0]
    nk = pl.cdiv(t_k, block_k)

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (block_q, block_k)
        if causal:
            # bottom-right alignment (same contract as _attention_reference's
            # tril(k=tk-tq)): query row i may see keys up to i + (tk - tq)
            offset = t_k - t_q_total
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        # -inf rows (fully masked so far) must not poison the rescale
        alpha = jnp.exp(jnp.where(m_prev == -jnp.inf, -jnp.inf, m_prev - m_new))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    d = q.shape[1]
    init = (
        jnp.zeros((block_q, d), jnp.float32),
        jnp.full((block_q,), -jnp.inf, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    if causal:
        # only KV blocks reaching this q block's last visible key contribute
        last_key = qi * block_q + block_q - 1 + (t_k - t_q_total)
        nk_needed = jnp.clip((last_key + block_k) // block_k, 0, nk)
    else:
        nk_needed = nk
    acc, m, l = jax.lax.fori_loop(0, nk_needed, body, init)
    o_ref[...] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        # logsumexp residual for the flash backward, broadcast across a
        # 128-lane dim (Mosaic's minimum tile width — the same residual
        # layout jax's official TPU flash kernel uses). Fully-masked rows
        # get a finite sentinel; their p = exp(-inf - lse) is 0 either way.
        lse = jnp.where(m == -jnp.inf, 0.0, m + jnp.log(jnp.maximum(l, 1e-20)))
        lse_ref[...] = jnp.broadcast_to(
            lse[:, None], lse_ref.shape
        ).astype(lse_ref.dtype)


def flash_tiles_ok(t, block=None):
    """Public predicate for _flash_forward's whole-tile condition: callers
    that REQUIRE the Pallas path (e.g. the flash ring, whose merge needs the
    lse the dense fallback doesn't produce) gate on this so the rule lives in
    one place with the fallback check below."""
    if t <= 0:
        return False
    bq = min(block or _DEF_BLOCK_Q, t)
    bk = min(block or _DEF_BLOCK_K, t)
    return t % bq == 0 and t % bk == 0


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                   with_lse=False):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if not (flash_tiles_ok(tq, block_q) and flash_tiles_ok(tk, block_k)):
        # ragged tails: fall back to the dense form (shapes are static, so
        # this is a trace-time decision, not a runtime branch)
        out = _attention_reference(q, k, v, causal, sm_scale)
        return (out, None) if with_lse else out
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    grid = (b * h, tq // block_q)
    out_shapes = [jax.ShapeDtypeStruct((b * h, tq, d), q.dtype)]
    out_specs = [pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0))]
    if with_lse:
        out_shapes.append(
            jax.ShapeDtypeStruct((b * h, tq, _LANES), jnp.float32)
        )
        out_specs.append(
            pl.BlockSpec((None, block_q, _LANES), lambda bh, qi: (bh, qi, 0))
        )
    res = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            q_block_idx_axis=1,
            t_q_total=tq,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shapes if with_lse else out_shapes[0],
        interpret=interpret,
    )(q3, k3, v3)
    if with_lse:
        out, lse = res
        return out.reshape(b, h, tq, d), lse[..., 0].reshape(b, h, tq)
    return res.reshape(b, h, tq, d)




# ---------------------------------------------------------------------------
# flash backward (flash-attention-2 style): dQ in one kernel over q blocks,
# dK/dV in another over k blocks, both streaming the opposite side and using
# the saved logsumexp L plus D = rowsum(dO * O)
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k, causal, sm_scale, t_q_total):
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[..., 0].astype(jnp.float32)
    delta = delta_ref[..., 0].astype(jnp.float32)
    block_q = q.shape[0]
    t_k = k_ref.shape[0]
    nk = pl.cdiv(t_k, block_k)

    def body(ki, dq):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            offset = t_k - t_q_total
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        last_key = qi * block_q + block_q - 1 + (t_k - t_q_total)
        nk_needed = jnp.clip((last_key + block_k) // block_k, 0, nk)
    else:
        nk_needed = nk
    dq = jax.lax.fori_loop(
        0, nk_needed, body, jnp.zeros((block_q, q.shape[1]), jnp.float32)
    )
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q, causal, sm_scale,
                          t_q_total):
    ki = pl.program_id(1)
    k_blk = k_ref[...].astype(jnp.float32)  # (block_k, d)
    v_blk = v_ref[...].astype(jnp.float32)
    block_k = k_blk.shape[0]
    t_k_total = pl.num_programs(1) * block_k
    offset = t_k_total - t_q_total  # bottom-right causal alignment
    t_q = q_ref.shape[0]
    nq = pl.cdiv(t_q, block_q)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qi * block_q, block_q), 0].astype(jnp.float32)
        delta = delta_ref[pl.ds(qi * block_q, block_q), 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    if causal:
        # q blocks whose last row still precedes this k block see nothing
        first_q_row = ki * block_k - offset
        q_start = jnp.clip(first_q_row // block_q, 0, nq)
    else:
        q_start = 0
    d = k_blk.shape[1]
    dk, dv = jax.lax.fori_loop(
        q_start,
        nq,
        body,
        (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32)),
    )
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, dout, causal, sm_scale, block_q,
                    block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    q3 = q.reshape(b * h, tq, d)
    k3 = k.reshape(b * h, tk, d)
    v3 = v.reshape(b * h, tk, d)
    do3 = dout.reshape(b * h, tq, d)
    lse3 = jnp.broadcast_to(
        lse.reshape(b * h, tq)[..., None], (b * h, tq, _LANES)
    )
    delta = jnp.broadcast_to(
        jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
        .reshape(b * h, tq)[..., None],
        (b * h, tq, _LANES),
    )

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            t_q_total=tq,
        ),
        grid=(b * h, tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            block_q=block_q,
            causal=causal,
            sm_scale=sm_scale,
            t_q_total=tq,
        ),
        grid=(b * h, tk // block_k),
        in_specs=[
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, tq, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta)

    return (
        dq.reshape(b, h, tq, d),
        dk.reshape(b, h, tk, d),
        dv.reshape(b, h, tk, d),
    )


def _resolve_defaults(q, sm_scale, interpret):
    """Single source of the defaulting rule: forward, _fwd and _bwd must
    agree or a custom_vjp would silently produce wrong gradients."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return sm_scale, interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    block_q=_DEF_BLOCK_Q,
    block_k=_DEF_BLOCK_K,
    interpret=None,
):
    """softmax(QKᵀ·scale [causal-masked]) V over (b, h, t, d) tensors."""
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    res = _flash_forward(
        q, k, v, causal, sm_scale, block_q, block_k, interpret, with_lse=True
    )
    out, lse = res
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    sm_scale, interpret = _resolve_defaults(q, sm_scale, interpret)
    if lse is None:
        # ragged-tail fallback: dense recompute-vjp (same trace-time decision
        # as the forward fallback)
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale), q, k, v
        )
        return vjp(dout)
    return _flash_backward(
        q, k, v, out, lse, dout, causal, sm_scale, block_q, block_k, interpret
    )


flash_attention.defvjp(_fwd, _bwd)


@register("flash_attention")
def _flash_attention_op(ctx, ins, attrs):
    """Graph-op form: Q/K/V (b, h, t, d) → Out. The transformer layers can
    emit this in place of the matmul+softmax+matmul chain."""
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    return {
        "Out": [
            flash_attention(
                q,
                k,
                v,
                bool(attrs.get("causal", False)),
                attrs.get("sm_scale"),
            )
        ]
    }
