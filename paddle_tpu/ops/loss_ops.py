"""Structured-prediction, ranking, and sampled losses.

Reference analogs (all under paddle/fluid/operators/):
- linear_chain_crf_op.cc / crf_decoding_op.cc — CRF log-likelihood + Viterbi
- warpctc_op.cc — CTC loss (reference binds libwarpctc; here a pure-JAX
  log-domain alpha recursion the MXU/VPU handle directly)
- ctc_align_op.cc — greedy-decode collapse (merge repeats, drop blanks)
- nce_op.cc — noise-contrastive estimation with uniform/log-uniform samplers
- hierarchical_sigmoid_op.cc + math/matrix_bit_code.h — hsigmoid over the
  implicit complete binary tree (SimpleCode)
- bpr_loss_op.cc, margin_rank_loss_op.cc, rank_loss_op.cc,
  modified_huber_loss_op.cc, cos_sim_op.cc
- edit_distance_op.cc — batched Levenshtein
- metrics/precision_recall_op.cc — streaming per-class TP/FP/TN/FN

Sequence inputs use the padded-dense [B, T, ...] + SeqLen convention
(sequence_ops.py); the reference's LoD-scattered layout is SURVEY.md §5.7.
"""

import jax
import jax.numpy as jnp
from jax import lax

from .core_ops import _opt_f32
from .registry import register


def _softplus(x):
    return jax.nn.softplus(x)


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------


def _crf_split_transition(transition):
    """reference linear_chain_crf_op.h: row 0 start weights, row 1 end
    weights, rows 2.. the (D, D) transition matrix."""
    return transition[0], transition[1], transition[2:]


@register("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    """Outputs the NEGATIVE log likelihood per sequence (the reference's
    LogLikelihood output is the minimization target, linear_chain_crf_op.h),
    plus Alpha/EmissionExps/TransitionExps for API parity."""
    (emission,) = ins["Emission"]  # [B, T, D] float
    (transition,) = ins["Transition"]  # [D+2, D]
    (label,) = ins["Label"]  # [B, T, 1] int
    (seqlen,) = ins["SeqLen"]  # [B]
    B, T, D = emission.shape
    label = label.reshape(B, T).astype(jnp.int32)
    seqlen = seqlen.reshape(-1).astype(jnp.int32)
    start, end, trans = _crf_split_transition(transition)

    e = emission.astype(jnp.float32)
    t_steps = jnp.arange(T, dtype=jnp.int32)

    # forward (log-alpha) recursion, masked past each row's length
    def step(alpha, sc):
        t, e_t = sc
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) + e_t
        active = (t < seqlen).reshape(B, 1)
        alpha = jnp.where(active, nxt, alpha)
        return alpha, alpha

    alpha0 = start[None] + e[:, 0]
    alpha_last, alphas = lax.scan(
        step, alpha0, (t_steps[1:], jnp.swapaxes(e, 0, 1)[1:])
    )
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, D]
    log_z = jax.nn.logsumexp(alpha_last + end[None], axis=1)  # [B]

    # gold-path score
    emit_sc = jnp.take_along_axis(e, label[:, :, None], axis=2).reshape(B, T)
    t_mask = t_steps[None, :] < seqlen[:, None]
    emit_score = jnp.sum(jnp.where(t_mask, emit_sc, 0.0), axis=1)
    pair_sc = trans[label[:, :-1], label[:, 1:]]  # [B, T-1]
    pair_mask = (t_steps[None, 1:] < seqlen[:, None])
    trans_score = jnp.sum(jnp.where(pair_mask, pair_sc, 0.0), axis=1)
    last_idx = jnp.maximum(seqlen - 1, 0)
    last_tag = jnp.take_along_axis(label, last_idx[:, None], axis=1).reshape(B)
    score = start[label[:, 0]] + emit_score + trans_score + end[last_tag]

    nll = (log_z - score).reshape(B, 1)
    return {
        "LogLikelihood": [nll],
        "Alpha": [jnp.swapaxes(alphas, 0, 1)],
        "EmissionExps": [jnp.exp(e)],
        "TransitionExps": [jnp.exp(transition.astype(jnp.float32))],
    }


@register("crf_decoding", no_grad=True)
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (reference crf_decoding_op.h). With a Label input the
    output marks per-position correctness instead (the reference behavior used
    by chunk evaluation)."""
    (emission,) = ins["Emission"]
    (transition,) = ins["Transition"]
    (seqlen,) = ins["SeqLen"]
    B, T, D = emission.shape
    seqlen = seqlen.reshape(-1).astype(jnp.int32)
    start, end, trans = _crf_split_transition(transition)
    e = emission.astype(jnp.float32)
    t_steps = jnp.arange(T, dtype=jnp.int32)

    def step(carry, sc):
        t, e_t = sc
        delta = carry
        cand = delta[:, :, None] + trans[None]  # [B, D_prev, D]
        best_prev = jnp.argmax(cand, axis=1).astype(jnp.int32)
        nxt = jnp.max(cand, axis=1) + e_t
        active = (t < seqlen).reshape(B, 1)
        delta = jnp.where(active, nxt, delta)
        # inactive rows point back at themselves so backtrace passes through
        self_ptr = jnp.broadcast_to(jnp.arange(D, dtype=jnp.int32), (B, D))
        best_prev = jnp.where(active, best_prev, self_ptr)
        return delta, best_prev

    delta0 = start[None] + e[:, 0]
    delta_last, back = lax.scan(
        step, delta0, (t_steps[1:], jnp.swapaxes(e, 0, 1)[1:])
    )  # back: [T-1, B, D]
    last_tag = jnp.argmax(delta_last + end[None], axis=1).astype(jnp.int32)

    def backstep(tag, ptr):
        prev = jnp.take_along_axis(ptr, tag[:, None], axis=1).reshape(B)
        return prev, tag

    _, path_rev = lax.scan(backstep, last_tag, back, reverse=True)
    first_tag = _  # tag at t=0 after full backtrace
    path = jnp.concatenate([first_tag[None], path_rev], axis=0)  # [T, B]
    path = jnp.swapaxes(path, 0, 1)  # [B, T]
    t_mask = t_steps[None, :] < seqlen[:, None]
    path = jnp.where(t_mask, path, 0)

    label = ins.get("Label", [None])[0]
    if label is not None:
        lbl = label.reshape(B, T).astype(jnp.int32)
        path = jnp.where(t_mask, (path == lbl).astype(jnp.int32), 0)
    return {"ViterbiPath": [path[:, :, None].astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------


@register("warpctc")
def _warpctc(ctx, ins, attrs):
    """CTC loss, log-domain alpha recursion over the blank-extended label
    (reference warpctc_op.cc via libwarpctc; Graves 2006 eq. 6-8)."""
    (logits,) = ins["Logits"]  # [B, T, C]
    (label,) = ins["Label"]  # [B, L, 1] int
    (logits_len,) = ins["LogitsLength"]
    (label_len,) = ins["LabelLength"]
    blank = int(attrs.get("blank", 0))
    norm_by_times = bool(attrs.get("norm_by_times", False))

    B, T, C = logits.shape
    L = label.shape[1]
    S = 2 * L + 1
    label = label.reshape(B, L).astype(jnp.int32)
    logits_len = logits_len.reshape(-1).astype(jnp.int32)
    label_len = label_len.reshape(-1).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=2)

    NEG = jnp.float32(-1e30)
    s_idx = jnp.arange(S, dtype=jnp.int32)
    # extended sequence: even slots blank, odd slots label[s//2]
    lab_idx = jnp.minimum(jnp.broadcast_to(s_idx[None, :] // 2, (B, S)), L - 1)
    ext = jnp.where(
        s_idx[None, :] % 2 == 0, blank, jnp.take_along_axis(label, lab_idx, axis=1)
    )  # [B, S]
    ext_valid = s_idx[None, :] < (2 * label_len[:, None] + 1)

    # skip-transition allowed where ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext, axis=1)  # [B, S]

    a0 = jnp.full((B, S), NEG)
    a0 = a0.at[:, 0].set(logp[:, 0, blank])
    first_lab = jnp.take_along_axis(logp[:, 0], ext[:, 1:2], axis=1).reshape(B)
    a0 = a0.at[:, 1].set(jnp.where(label_len > 0, first_lab, NEG))

    def lse2(a, b):
        return jnp.logaddexp(a, b)

    def step(alpha, t):
        sh1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        sh2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        acc = lse2(alpha, sh1)
        acc = jnp.where(can_skip, lse2(acc, sh2), acc)
        nxt = acc + emit(t)
        nxt = jnp.where(ext_valid, nxt, NEG)
        active = (t < logits_len).reshape(B, 1)
        return jnp.where(active, nxt, alpha), None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, T, dtype=jnp.int32))

    end1 = 2 * label_len  # final blank slot
    end2 = jnp.maximum(2 * label_len - 1, 0)  # final label slot
    ll = jnp.logaddexp(
        jnp.take_along_axis(alpha, end1[:, None], axis=1).reshape(B),
        jnp.where(
            label_len > 0,
            jnp.take_along_axis(alpha, end2[:, None], axis=1).reshape(B),
            NEG,
        ),
    )
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(logits_len.astype(jnp.float32), 1.0)
    return {"Loss": [loss.reshape(B, 1)]}


@register("ctc_align", no_grad=True)
def _ctc_align(ctx, ins, attrs):
    """Collapse repeats then drop blanks (reference ctc_align_op.cc). Output
    stays padded [B, T, 1] with an OutLen companion; removed slots are filled
    with padding_value."""
    (x,) = ins["Input"]  # [B, T, 1] int tokens
    (seqlen,) = ins["SeqLen"]
    blank = int(attrs.get("blank", 0))
    pad_val = int(attrs.get("padding_value", 0))
    B, T = x.shape[0], x.shape[1]
    tok = x.reshape(B, T).astype(jnp.int32)
    seqlen = seqlen.reshape(-1).astype(jnp.int32)
    t_idx = jnp.arange(T, dtype=jnp.int32)
    valid = t_idx[None, :] < seqlen[:, None]
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), tok[:, :-1]], axis=1)
    keep = (tok != blank) & (tok != prev) & valid
    # stable-compact kept tokens to the front of each row
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(tok, order, axis=1)
    out_len = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(t_idx[None, :] < out_len[:, None], compacted, pad_val)
    return {"Output": [out[:, :, None].astype(x.dtype)], "OutLen": [out_len]}


# ---------------------------------------------------------------------------
# sampled losses
# ---------------------------------------------------------------------------


def _log_uniform_probs(C):
    k = jnp.arange(C, dtype=jnp.float32)
    return (jnp.log(k + 2.0) - jnp.log(k + 1.0)) / jnp.log(C + 1.0)


def _draw_samples(key, sampler, C, S, probs=None):
    if sampler == "log_uniform":
        u = jax.random.uniform(key, (S,))
        # inverse CDF of P(k) ∝ log((k+2)/(k+1)): k = floor((C+1)^u) - 1
        s = jnp.floor(jnp.exp(u * jnp.log(C + 1.0))).astype(jnp.int32) - 1
        return jnp.clip(s, 0, C - 1)
    if sampler == "custom_dist":
        # categorical over the user distribution (the reference's alias-table
        # CustomSampler is a CPU sampling trick; the distribution is probs)
        return jax.random.categorical(key, jnp.log(probs + 1e-20), shape=(S,)).astype(
            jnp.int32
        )
    return jax.random.randint(key, (S,), 0, C)


@register("nce", stochastic=True)
def _nce(ctx, ins, attrs):
    """NCE logistic loss with shared negative samples (reference nce_op.h:
    uniform, log-uniform, or custom_dist sampler; optional per-row
    SampleWeight scaling the cost, nce_op.h:159)."""
    (x,) = ins["Input"]  # [B, D]
    (label,) = ins["Label"]  # [B, num_true]
    (w,) = ins["Weight"]  # [C, D]
    bias = ins.get("Bias", [None])[0]  # [C]
    sample_weight = ins.get("SampleWeight", [None])[0]  # [B]
    C = int(attrs["num_total_classes"])
    S = int(attrs.get("num_neg_samples", 10))
    sampler = attrs.get("sampler", "uniform")
    B = x.shape[0]
    label = label.reshape(B, -1).astype(jnp.int32)
    num_true = label.shape[1]

    if sampler == "log_uniform":
        probs = _log_uniform_probs(C)
    elif sampler == "custom_dist":
        (probs,) = ins["CustomDistProbs"]
        probs = probs.reshape(-1).astype(jnp.float32)
        probs = probs / jnp.sum(probs)
    else:
        probs = jnp.full((C,), 1.0 / C)

    neg = _draw_samples(ctx.next_rng(), sampler, C, S, probs)  # [S]

    # gather only the sampled rows of W — never the full [B, C] logits
    pos_logit = jnp.einsum("bd,btd->bt", x, w[label])  # [B, num_true]
    neg_logit = jnp.einsum("bd,sd->bs", x, w[neg])  # [B, S]
    if bias is not None:
        pos_logit = pos_logit + bias.reshape(-1)[label]
        neg_logit = neg_logit + bias.reshape(-1)[neg][None, :]

    # logistic NCE: subtract log expected count under the noise distribution
    pos_adj = pos_logit - jnp.log(S * probs[label] + 1e-12)
    neg_adj = neg_logit - jnp.log(S * probs[neg][None, :] + 1e-12)
    cost = jnp.sum(_softplus(-pos_adj), axis=1) / num_true + jnp.sum(
        _softplus(neg_adj), axis=1
    )
    if sample_weight is not None:
        cost = cost * sample_weight.reshape(B).astype(cost.dtype)
    return {
        "Cost": [cost.reshape(B, 1)],
        "SampleLogits": [jnp.concatenate([pos_adj, neg_adj], axis=1)],
        "SampleLabels": [
            jnp.concatenate(
                [label, jnp.broadcast_to(neg[None, :], (B, S))], axis=1
            ).astype(jnp.int64)
        ],
    }


@register("hierarchical_sigmoid")
def _hsigmoid(ctx, ins, attrs):
    """Complete-binary-tree hsigmoid (reference hierarchical_sigmoid_op.h +
    math/matrix_bit_code.h SimpleCode: c = label + C, index_j = (c>>(j+1))-1,
    bit_j = (c>>j)&1, path length = highest set bit)."""
    (x,) = ins["X"]  # [B, D]
    (w,) = ins["W"]  # [C-1, D]
    (label,) = ins["Label"]  # [B, 1]
    bias = ins.get("Bias", [None])[0]  # [C-1]
    C = int(attrs["num_classes"])
    B, D = x.shape
    label = label.reshape(B).astype(jnp.int32)
    c = label + C
    max_len = max(int.bit_length(2 * C - 1) - 1, 1)
    j = jnp.arange(max_len, dtype=jnp.int32)  # [J]
    length = jnp.floor(jnp.log2(c.astype(jnp.float32))).astype(jnp.int32)
    on_path = j[None, :] < length[:, None]  # [B, J]
    idx = jnp.clip((c[:, None] >> (j[None, :] + 1)) - 1, 0, C - 2)
    bit = ((c[:, None] >> j[None, :]) & 1).astype(jnp.float32)
    t = jnp.einsum("bd,bjd->bj", x, w[idx])
    if bias is not None:
        t = t + bias.reshape(-1)[idx]
    pre = jnp.where(on_path, t, 0.0)
    cost = jnp.sum(jnp.where(on_path, _softplus(t) - bit * t, 0.0), axis=1)
    return {"Cost": [cost.reshape(B, 1)], "PreOut": [pre]}


@register("sampling_id", no_grad=True, stochastic=True)
def _sampling_id(ctx, ins, attrs):
    """Sample a column index per row from a probability matrix (reference
    sampling_id_op.cc)."""
    (x,) = ins["X"]  # [B, C] probabilities
    key = ctx.next_rng()
    ids = jax.random.categorical(key, jnp.log(x + 1e-20), axis=1)
    return {"Out": [ids.astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# ranking / misc losses
# ---------------------------------------------------------------------------


@register("bpr_loss")
def _bpr_loss(ctx, ins, attrs):
    """Bayesian personalized ranking (reference bpr_loss_op.h): mean over
    j != label of softplus(x_j - x_label)."""
    (x,) = ins["X"]  # [B, C]
    (label,) = ins["Label"]  # [B, 1]
    B, C = x.shape
    lbl = label.reshape(B, 1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lbl, axis=1)  # [B, 1]
    diff = _softplus(x - pos)  # softplus(0)=log2 at j=label, subtracted below
    cost = (jnp.sum(diff, axis=1) - _softplus(jnp.zeros(()))) / (C - 1)
    return {"Cost": [cost.reshape(B, 1)]}


@register("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    (x1,) = ins["X1"]
    (x2,) = ins["X2"]
    (label,) = ins["Label"]  # +1: x1 ranks higher, -1: x2
    margin = float(attrs.get("margin", 0.0))
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register("rank_loss")
def _rank_loss(ctx, ins, attrs):
    """RankNet pairwise loss (reference rank_loss_op.cc): o = left-right,
    C = softplus(o) - label*o."""
    (label,) = ins["Label"]
    (left,) = ins["Left"]
    (right,) = ins["Right"]
    o = left - right
    return {"Out": [_softplus(o) - label * o]}


@register("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    """reference modified_huber_loss_op.h: y in {0,1} mapped to ±1; z=y*x;
    quadratic in [-1, inf), linear below."""
    (x,) = ins["X"]
    (y,) = ins["Y"]
    yy = 2.0 * y - 1.0
    z = yy * x
    out = jnp.where(z < -1.0, -4.0 * z, jnp.square(jnp.maximum(0.0, 1.0 - z)))
    return {"Out": [out], "IntermediateVal": [z]}


@register("cos_sim")
def _cos_sim(ctx, ins, attrs):
    """reference cos_sim_op.h; Y may have 1 row (broadcast over the batch)."""
    (x,) = ins["X"]  # [B, D]
    (y,) = ins["Y"]  # [B, D] or [1, D]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    dot = jnp.sum(x * y, axis=1, keepdims=True)
    out = dot / (xn * yn + 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


# ---------------------------------------------------------------------------
# evaluation ops
# ---------------------------------------------------------------------------


@register("edit_distance", no_grad=True)
def _edit_distance(ctx, ins, attrs):
    """Batched Levenshtein distance (reference edit_distance_op.h), DP row
    recursion scanned over hypothesis positions."""
    (hyp,) = ins["Hyps"]  # [B, T1, 1] int
    (ref,) = ins["Refs"]  # [B, T2, 1] int
    (hyp_len,) = ins["HypsLen"]
    (ref_len,) = ins["RefsLen"]
    normalized = bool(attrs.get("normalized", True))
    B, T1 = hyp.shape[0], hyp.shape[1]
    T2 = ref.shape[1]
    hyp = hyp.reshape(B, T1).astype(jnp.int32)
    ref = ref.reshape(B, T2).astype(jnp.int32)
    hyp_len = hyp_len.reshape(-1).astype(jnp.int32)
    ref_len = ref_len.reshape(-1).astype(jnp.int32)

    j_idx = jnp.arange(T2 + 1, dtype=jnp.float32)
    row0 = jnp.broadcast_to(j_idx, (B, T2 + 1))

    def step(row, sc):
        i, h_i = sc  # i: 1-based hyp position, h_i: [B]
        sub_cost = (ref != h_i[:, None]).astype(jnp.float32)  # [B, T2]
        # new_row[0] = i; new_row[j] = min(row[j]+1, new_row[j-1]+1, row[j-1]+sub)
        del_c = row[:, 1:] + 1.0
        sub_c = row[:, :-1] + sub_cost

        def inner(prev, cols):
            d, s = cols
            cur = jnp.minimum(jnp.minimum(d, prev + 1.0), s)
            return cur, cur

        init = jnp.full((B,), i, jnp.float32)
        _, rest = lax.scan(
            inner, init, (jnp.swapaxes(del_c, 0, 1), jnp.swapaxes(sub_c, 0, 1))
        )
        new_row = jnp.concatenate([init[:, None], jnp.swapaxes(rest, 0, 1)], axis=1)
        active = (i <= hyp_len.astype(jnp.float32)).reshape(B, 1)
        row = jnp.where(active, new_row, row)
        return row, None

    i_steps = jnp.arange(1, T1 + 1, dtype=jnp.float32)
    final, _ = lax.scan(step, row0, (i_steps, jnp.swapaxes(hyp, 0, 1).astype(jnp.float32)))
    dist = jnp.take_along_axis(final, ref_len[:, None], axis=1).reshape(B)
    if normalized:
        dist = dist / jnp.maximum(ref_len.astype(jnp.float32), 1.0)
    return {
        "Out": [dist.reshape(B, 1)],
        "SequenceNum": [jnp.asarray([B], jnp.int64)],
    }


@register("precision_recall", no_grad=True)
def _precision_recall(ctx, ins, attrs):
    """Streaming macro/micro precision/recall/F1 (reference
    metrics/precision_recall_op.h). States are per-class [TP, FP, TN, FN]."""
    (idx,) = ins["Indices"]  # [B, 1] predicted class
    (labels,) = ins["Labels"]  # [B, 1]
    states = ins.get("StatesInfo", [None])[0]  # [C, 4] accumulated
    C = int(attrs["class_number"])
    B = idx.shape[0]
    pred = jax.nn.one_hot(idx.reshape(B).astype(jnp.int32), C)
    true = jax.nn.one_hot(labels.reshape(B).astype(jnp.int32), C)
    tp = jnp.sum(pred * true, axis=0)
    fp = jnp.sum(pred * (1 - true), axis=0)
    fn = jnp.sum((1 - pred) * true, axis=0)
    tn = jnp.sum((1 - pred) * (1 - true), axis=0)
    batch = jnp.stack([tp, fp, tn, fn], axis=1)  # [C, 4]
    acc = batch if states is None else batch + states

    def metrics(st):
        tp_, fp_, _, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12), 0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mprec = jnp.where(stp + sfp > 0, stp / (stp + sfp + 1e-12), 0.0)
        mrec = jnp.where(stp + sfn > 0, stp / (stp + sfn + 1e-12), 0.0)
        mf1 = jnp.where(
            mprec + mrec > 0, 2 * mprec * mrec / (mprec + mrec + 1e-12), 0.0
        )
        return jnp.concatenate([macro, jnp.stack([mprec, mrec, mf1])])

    return {
        "BatchMetrics": [metrics(batch)],
        "AccumMetrics": [metrics(acc)],
        "AccumStatesInfo": [acc],
    }


# ---------------------------------------------------------------------------
# proximal optimizers (reference optimizers/proximal_gd_op.h,
# proximal_adagrad_op.h)
# ---------------------------------------------------------------------------


def _prox(p, lr, l1, l2):
    return (
        jnp.sign(p) * jnp.maximum(jnp.abs(p) - lr * l1, 0.0) / (1.0 + lr * l2)
    )


@register("proximal_gd", no_grad=True)
@_opt_f32
def _proximal_gd(ctx, ins, attrs):
    (p,) = ins["Param"]
    (g,) = ins["Grad"]
    (lr,) = ins["LearningRate"]
    l1, l2 = float(attrs.get("l1", 0.0)), float(attrs.get("l2", 0.0))
    lr = lr.reshape(())
    return {"ParamOut": [_prox(p - lr * g, lr, l1, l2)]}


@register("proximal_adagrad", no_grad=True)
@_opt_f32
def _proximal_adagrad(ctx, ins, attrs):
    (p,) = ins["Param"]
    (g,) = ins["Grad"]
    (m,) = ins["Moment"]
    (lr,) = ins["LearningRate"]
    l1, l2 = float(attrs.get("l1", 0.0)), float(attrs.get("l2", 0.0))
    m_out = m + jnp.square(g)
    lr = lr.reshape(())
    # grad step scales by lr/sqrt(moment), but the l1/l2 shrinkage uses the
    # plain scalar lr (reference proximal_adagrad_op.h)
    prox_param = p - lr * g / jnp.sqrt(m_out + 1e-10)
    return {"ParamOut": [_prox(prox_param, lr, l1, l2)], "MomentOut": [m_out]}


@register("average_accumulates", no_grad=True)
def _average_accumulates(ctx, ins, attrs):
    """Sliding-window parameter-sum accumulation for ModelAverage (reference
    operators/average_accumulates_op.h; kMaxNumAccumulates window shifting)."""
    (p,) = ins["Param"]
    sum_1, sum_2, sum_3 = ins["Sums"]
    num_acc, old_num_acc, num_upd = [c.reshape(()) for c in ins["Counters"]]
    avg_window = float(attrs.get("average_window", 0.0))
    min_w = int(attrs.get("min_average_window", 10000))
    max_w = int(attrs.get("max_average_window", 10000))
    K_MAX = 16384

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    sum_1 = sum_1 + p

    fold = num_upd % K_MAX == 0
    sum_2 = jnp.where(fold, sum_2 + sum_1, sum_2)
    sum_1 = jnp.where(fold, jnp.zeros_like(sum_1), sum_1)

    window = jnp.minimum(
        jnp.asarray(max_w, num_upd.dtype),
        (num_upd.astype(jnp.float32) * avg_window).astype(num_upd.dtype),
    )
    shift = (num_acc >= min_w) & (num_acc >= window)
    sum_3 = jnp.where(shift, sum_1 + sum_2, sum_3)
    sum_1 = jnp.where(shift, jnp.zeros_like(sum_1), sum_1)
    sum_2 = jnp.where(shift, jnp.zeros_like(sum_2), sum_2)
    old_num_acc = jnp.where(shift, num_acc, old_num_acc)
    num_acc = jnp.where(shift, jnp.zeros_like(num_acc), num_acc)

    return {
        "SumsOut": [sum_1, sum_2, sum_3],
        "CountersOut": [
            num_acc.reshape(1),
            old_num_acc.reshape(1),
            num_upd.reshape(1),
        ],
    }


@register("average_apply", no_grad=True)
def _average_apply(ctx, ins, attrs):
    """Swap a parameter for its windowed average, backing up the live value
    (ModelAverage.apply; reference optimizer.py _add_average_apply_op)."""
    (p,) = ins["Param"]
    sum_1, sum_2, sum_3 = ins["Sums"]
    num_acc, old_num_acc = [c.reshape(()) for c in ins["Counters"]]
    total = (num_acc + old_num_acc).astype(p.dtype)
    avg = (sum_1 + sum_2 + sum_3) / jnp.maximum(total, 1.0)
    return {"ParamOut": [avg.astype(p.dtype)], "Backup": [p]}
