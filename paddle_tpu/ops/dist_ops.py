"""Distributed RPC ops — host ops executed between XLA segments.

Reference analog: operators/distributed_ops/ — send_op (async grad push),
recv_op (param pull), send_barrier_op, fetch_barrier_op, listen_and_serv_op,
checkpoint_notify_op, fake_init_op. These are the reference's non-kernel
OperatorBase ops (they talk gRPC, not CUDA); here they run on the host between
the block's jitted XLA segments (executor.py partitions at host ops), speaking
the socket RPC in distributed/rpc.py.
"""

import numpy as np

from .registry import register_host


def _client(op):
    from ..distributed.rpc import RPCClient

    return RPCClient.instance(int(op.attrs.get("trainer_id", 0)))


@register_host("send")
def _send(op, scope):
    """Push each X[i] to epmap[i] (reference send_op.cc: AsyncSendVar per var,
    then Wait)."""
    client = _client(op)
    names = op.input("X")
    epmap = op.attrs["epmap"]
    for name, ep in zip(names, epmap):
        client.async_send_var(ep, name, np.asarray(scope.find_var(name)))
    client.wait()


@register_host("recv")
def _recv(op, scope):
    """Pull each Out[i] from epmap[i] (reference recv_op.cc)."""
    client = _client(op)
    names = op.output("Out")
    epmap = op.attrs["epmap"]
    futures = [(name, client.async_get_var(ep, name)) for name, ep in zip(names, epmap)]
    import jax.numpy as jnp

    for name, f in futures:
        arr = f.result(timeout=client.timeout)
        if arr is None:
            raise KeyError(
                "recv: pserver has no var %r (wrong endpoint map?)" % name
            )
        scope.set_var(name, jnp.asarray(arr))


@register_host("send_barrier")
def _send_barrier(op, scope):
    client = _client(op)
    for ep in op.attrs["endpoints"]:
        client.send_barrier(ep)
    client.wait()


@register_host("fetch_barrier")
def _fetch_barrier(op, scope):
    client = _client(op)
    for ep in op.attrs["endpoints"]:
        client.fetch_barrier(ep)
    client.wait()


@register_host("listen_and_serv")
def _listen_and_serv(op, scope):
    from ..distributed.listen_and_serv import run_pserver

    run_pserver(op, scope)


@register_host("checkpoint_notify")
def _checkpoint_notify(op, scope):
    """Ask each pserver to checkpoint its shards (reference
    checkpoint_notify_op.cc + RequestCheckpointHandler). Served over the same
    GET channel: the pserver saves on demand via its save hook if installed."""
    ckpt_dir = op.attrs.get("dir", "")
    if not ckpt_dir:
        raise ValueError("checkpoint_notify requires a non-empty 'dir' attr")
    client = _client(op)
    futures = [
        (ep, client.async_get_var(ep, "__checkpoint__:%s" % ckpt_dir))
        for ep in op.attrs.get("epmap", op.attrs.get("endpoints", []))
    ]
    client.wait()
    for ep, f in futures:
        if f.result() is None:
            raise RuntimeError("pserver %s failed to checkpoint to %r" % (ep, ckpt_dir))


@register_host("fake_init")
def _fake_init(op, scope):
    """Declare-only init for vars whose values live on pservers (reference
    fake_init_op.cc): creates an empty placeholder so startup programs type-
    check; real values arrive via recv."""
    import jax.numpy as jnp

    for name in op.output("Out"):
        if scope.find_var(name) is None:
            scope.set_var(name, jnp.zeros((1,), jnp.float32))


# ---------------------------------------------------------------------------
# in-graph distributed selection (not RPC: lowers to XLA like any tensor op)
# ---------------------------------------------------------------------------


def _register_ref_by_trainer_id():
    # local import: keep the host-op section above import-light (this module
    # loads even where jax is absent-but-stubbed during docs builds)
    import jax.numpy as jnp
    from jax import lax

    from .registry import register

    @register("ref_by_trainer_id", no_grad=True)
    def _ref_by_trainer_id(ctx, ins, attrs):
        """Out = X[TrainerId] (reference ref_by_trainer_id_op.cc): each
        trainer selects its own row from a list of same-shaped candidates —
        the reference used it to hand trainer-k its slice of a split
        parameter/LR schedule. All inputs must agree in shape (the reference
        indexes a vector of pre-split vars the transpiler sized equally)."""
        xs = ins["X"]
        (tid,) = ins["TrainerId"]
        idx = jnp.clip(tid.reshape(()).astype(jnp.int32), 0, len(xs) - 1)
        out = lax.dynamic_index_in_dim(jnp.stack(xs), idx, 0, keepdims=False)
        return {"Out": [out]}


_register_ref_by_trainer_id()
