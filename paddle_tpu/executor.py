"""Executor: lowers a whole block to ONE jitted XLA computation and runs it.

Reference analog: paddle/fluid/framework/executor.cc:158 — but where the
reference interprets the block op-by-op (executor.cc:389-396, each op a
separate kernel launch), this executor generalizes the reference's nGraph seam
(executor.cc:91-107, its only "compile a region" precedent) to the WHOLE block:
every op's JAX lowering is stitched into a single traced function, jitted once
per (program version, feed shapes) and cached like the reference's Python
program cache (reference executor.py:285).

Mutability model: reference ops mutate named Variables in a Scope. Here the
trace threads an immutable name->array environment; an op "writes" a var by
rebinding the name. Persistable vars written by the block (params, optimizer
state, batch-norm running stats) come in as a donated pytree argument and go
out as updated state — giving in-place buffer semantics on TPU without mutable
aliasing inside XLA.

Scope (reference framework/scope.h) holds name -> jax.Array plus the PRNG key
that stochastic ops consume.
"""

import itertools
import sys
import threading
import time
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from . import framework
from .framework import Program, Variable, convert_np_dtype
from .ops import registry
from .ops.registry import EMPTY_VAR_NAME

__all__ = [
    "Executor",
    "Scope",
    "global_scope",
    "scope_guard",
    "aot_serve_lowering",
]


def _flags_profile_ops():
    from . import flags as _flags

    return _flags.get_flags("profile_ops")["profile_ops"]


def _flags_opprof():
    """The op-attribution flags (observability/opprof.py) in ONE flags
    lookup — the entire hot-path cost of the feature when disabled."""
    from . import flags as _flags

    return _flags.get_flags(("tensor_stats", "nan_provenance"))


def _apply_pass_pipeline(program, scope, feed_names, fetch_names, pipeline=None):
    """The single choke point where graph passes rewrite a program before
    lowering (paddle_tpu/passes, docs/passes.md). Both executors and
    aot_serve_lowering route through here. `pipeline` None defers to
    FLAGS_pass_pipeline; ""/"off"/() disables and returns the program
    untouched. The transformed program is memoized per (program version,
    pipeline, scope, feed/fetch), so repeated runs hand the executor the
    SAME object and its compile cache stays hot."""
    if pipeline is None:
        from . import flags as _flags

        pipeline = _flags.get_flags("pass_pipeline")["pass_pipeline"]
    from .passes import manager as _pm

    if not _pm.resolve_pipeline(pipeline):
        return program
    out = _pm.apply_cached(
        program, pipeline, scope=scope,
        feed_names=feed_names, fetch_names=fetch_names,
    )
    # sharding rules live on the Program OBJECT (parallel.sharding_rules
    # .program_rules); the rewritten program shares the source's rule set so
    # placement survives the pipeline (mutations propagate — the executor
    # cache key carries the rules' fingerprint)
    rules = getattr(program, "_sharding_rules", None)
    if out is not program and rules is not None:
        out._sharding_rules = rules
    return out


def _compiled_ops(compiled):
    """The fluid op list behind any compiled-block flavor (for NaN
    provenance and the check_nan_inf last-writer report)."""
    ops = getattr(compiled, "ops", None)
    if ops is None:
        inner = getattr(compiled, "_inner", None)  # _MultiStepBlock
        ops = getattr(inner, "ops", None)
    if ops is None:
        blk = getattr(compiled, "block", None)  # _SegmentedBlock
        ops = blk.ops if blk is not None else None
    return ops or ()


def _last_writer(compiled, var_name):
    """Display name of the LAST op in program order writing `var_name`, or
    None — names the suspect in the check_nan_inf report (ops are anonymous;
    the variable is the only handle the error has)."""
    from .observability import opprof as _opprof

    found = None
    try:
        for op in _compiled_ops(compiled):
            if var_name in op.output_arg_names:
                found = _opprof.op_display_name(op)
    except Exception:
        return None
    return found


def _localize_nan(compiled, scope, feed_arrays, rng_key, reason, step=None,
                  mut_override=None):
    """FLAGS_nan_provenance driver: replay the failed step's feed through
    opprof.localize_nonfinite over the block's op list, against the step's
    PRE-state (`mut_override` = the guard's pre-step snapshot when it has
    one, else the scope as-is) and pre-step rng key. Returns the written
    provenance record or None; never raises (diagnosis must not mask the
    original failure)."""
    ops = _compiled_ops(compiled)
    if not ops:
        return None
    if isinstance(compiled, _MultiStepBlock):
        # a k-step scan's feed is stacked [k, ...]; replaying it as one
        # step would walk garbage shapes — provenance needs steps_per_run=1
        if not getattr(_localize_nan, "_warned_multi", False):
            _localize_nan._warned_multi = True
            print(
                "[nan_provenance] skipped: steps_per_run>1 runs cannot be "
                "replayed per-op (rerun the failing step with "
                "steps_per_run=1)", file=sys.stderr,
            )
        return None
    from .observability import opprof as _opprof

    try:
        env = {n: v for n, v in scope.vars.items() if v is not None}
        if mut_override:
            for n, v in mut_override.items():
                env[n] = jnp.asarray(v)
        feed_want = getattr(compiled, "_feed_want", {})
        for n, v in feed_arrays.items():
            a = v if isinstance(v, jax.Array) else jnp.asarray(v)
            want = feed_want.get(n)
            if want is not None and a.dtype != want:
                a = a.astype(want)
            env[n] = a
        prov = _opprof.localize_nonfinite(
            ops, env, rng_key if rng_key is not None else scope.rng_key,
            step=step,
        )
        if prov is None:
            return None
        return _opprof.write_provenance(prov, reason=reason)
    except Exception as e:
        if not getattr(_localize_nan, "_warned", False):
            _localize_nan._warned = True
            print("[nan_provenance] replay failed: %r" % e, file=sys.stderr)
        return None


_ELASTIC_HB = None


def _elastic_heartbeat():
    """Beat the elastic watchdog (resilience/elastic.py). The import is
    resolved once and cached; afterwards the disabled path is one function
    call + one empty-list probe per run."""
    global _ELASTIC_HB
    hb = _ELASTIC_HB
    if hb is None:
        from .resilience.elastic import heartbeat as hb

        _ELASTIC_HB = hb
    hb()


def _telemetry_begin():
    """(collector, t0) when telemetry is active, else (None, None) — the
    disabled path costs one flags lookup per run (observability.stepstats)."""
    from .observability import stepstats as _ss

    if not _ss.active():
        return None, None
    return _ss.collector(), time.perf_counter()


def _telemetry_record(obs, t0, compiled, cache_hit, nan_trip, n_steps,
                      result, return_numpy, pp=None, n_micro=None,
                      schedule=None):
    """Shared Executor/ParallelExecutor step-record tail. Loss is extracted
    best-effort from the first fetch ONLY when it is already host-side
    (return_numpy) — telemetry must never add a device sync of its own. A
    telemetry failure (e.g. export-dir IO) must never fail the step: it is
    reported once and swallowed."""
    wall_ms = (time.perf_counter() - t0) * 1e3
    loss = None
    if return_numpy and result:
        try:
            a = np.asarray(result[0])
            if a.size >= 1 and a.dtype.kind == "f":
                # multi-step fetches come back [k, ...]: report the last step
                loss = float(a.reshape(-1)[-1])
        except (TypeError, ValueError):
            pass
    try:
        obs.record_step(
            wall_ms, n_steps=n_steps, cache_hit=cache_hit, nan_trip=nan_trip,
            pp=pp, n_micro=n_micro, schedule=schedule, loss=loss,
            training=bool(getattr(compiled, "mut_names", ())),
        )
    except Exception as e:
        if not getattr(_telemetry_record, "_warned", False):
            _telemetry_record._warned = True
            print("telemetry record failed (disabled for this message): %r"
                  % e, file=sys.stderr)


class Scope:
    """name -> device array store (reference scope.h:134, flat not hierarchical
    — per-iteration locals are SSA temporaries inside the jitted function, so
    child scopes are unnecessary)."""

    _uid_counter = itertools.count()

    def __init__(self, seed=0):
        self.vars = {}
        self._seed = seed
        self._rng_key = None  # lazy: creating a key initializes the backend
        # monotonic uid for executable-cache keys (id() can be reused after GC)
        self._uid = next(Scope._uid_counter)

    @property
    def rng_key(self):
        if self._rng_key is None:
            self._rng_key = jax.random.key(self._seed)
        return self._rng_key

    @rng_key.setter
    def rng_key(self, value):
        self._rng_key = value

    def find_var(self, name):
        return self.vars.get(name)

    def var_names(self):
        """reference scope.h LocalVarNames()"""
        return list(self.vars)

    def var(self, name):
        return self.vars.setdefault(name, None)

    def set_var(self, name, value):
        self.vars[name] = value

    def drop_kids(self):  # compat no-op
        pass


_global_scope = Scope()
_scope_tls = threading.local()


def _scope_stack():
    # per-thread stack (pserver serving loops and AsyncExecutor workers each
    # run under their own scope_guard concurrently; the reference's Scope use
    # is likewise per-thread)
    st = getattr(_scope_tls, "stack", None)
    if st is None:
        st = _scope_tls.stack = [_global_scope]
    return st


def global_scope():
    return _scope_stack()[-1]


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack().append(self.scope)

    def __exit__(self, *args):
        _scope_stack().pop()


def _as_feed_array(value, var):
    want = None
    if var is not None and var.dtype is not None:
        want = jnp.bfloat16 if var.dtype == "bfloat16" else np.dtype(var.dtype)
    if isinstance(value, jax.Array):
        # device-resident feed: any needed cast happens inside the compiled
        # block (_CompiledBlock.run), where it fuses into the step instead of
        # costing an eager per-step device dispatch. Paths that do NOT run
        # through _CompiledBlock.run (per-op profiling, host-op segmented
        # programs) eager-cast via _eager_cast_feeds below.
        return value
    arr = np.asarray(value)
    if want is not None:
        arr = arr.astype(want)
    return arr


def _eager_cast_feeds(block, feed_arrays):
    """Cast device-resident feeds to their declared var dtypes NOW — for
    execution paths that bypass _CompiledBlock.run's fused trace-time cast
    (_PerOpProfiledBlock, _SegmentedBlock), which consume env values
    directly."""
    out = {}
    for name, value in feed_arrays.items():
        if isinstance(value, jax.Array):
            var = block.vars.get(name)
            if var is not None and var.dtype is not None:
                want = jnp.dtype(
                    jnp.bfloat16 if var.dtype == "bfloat16" else np.dtype(var.dtype)
                )
                if value.dtype != want:
                    value = value.astype(want)
        out[name] = value
    return out


class _CompiledBlock:
    """A lowered + jitted block: knows its state split (read-only vs mutated
    persistables) and fetch names.

    With `mesh`, the same lowering compiles SPMD (the TPU-native replacement
    for the reference's ParallelExecutor SSA graph + NCCL, SURVEY.md §2.2):
    feeds are batch-sharded over the mesh's data axes, state is replicated,
    and XLA's GSPMD partitioner inserts the gradient all-reduce over ICI at
    the same seam where the reference's multi_devices_graph_pass inserted
    ncclAllReduce ops.

    With `zero1_axis` (ParallelExecutor under ReduceStrategy.Reduce), the
    optimizer tier runs ZeRO-1 sharded over that axis: optimizer-state
    tensors (momentum velocities, Adam moments — core_ops.ZERO1_STATE_SLOTS)
    are STORED sharded 1/dp per rank via their in/out_shardings, and the
    optimizer lowerings (core_ops._opt_f32 reading ctx.zero1_axis) constrain
    grad/param/moments so GSPMD emits reduce-scatter + sharded update +
    param all-gather in place of the gradient all-reduce — identical wire
    volume, optimizer-state memory and HBM traffic ÷ dp
    (docs/parallelism.md)."""

    def __init__(self, program, block, feed_names, fetch_names, scope, mesh=None,
                 data_axes=("dp",), feed_ranks=None, ops_override=None,
                 zero1_axis=None, sharding_rules=None, instrument=True):
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        src_ops = block.ops if ops_override is None else ops_override
        ops = [
            op
            for op in src_ops
            if not registry.get(op.type).skip_exec
        ] if all(registry.is_registered(op.type) for op in src_ops) else None
        if ops is None:
            unknown = [op.type for op in src_ops if not registry.is_registered(op.type)]
            raise NotImplementedError("ops without lowering: %s" % sorted(set(unknown)))
        if any(registry.get(op.type).is_host for op in ops):
            raise RuntimeError(
                "host ops (send/recv/listen_and_serv...) cannot be jitted; "
                "run this block through Executor, which partitions at host ops"
            )
        self.ops = ops

        # classify external inputs: fed names are args; persistable names found
        # in the scope are state; anything else must be produced by the block.
        produced = set()
        state_names = []
        fed = set(self.feed_names)
        for op in self.ops:
            for name in op.input_arg_names:
                if name == EMPTY_VAR_NAME:
                    continue
                if name in fed or name in produced or name in state_names:
                    continue
                if scope.find_var(name) is not None:
                    state_names.append(name)
                else:
                    v = block.has_var_recursive(name) and block._var_recursive(name)
                    raise RuntimeError(
                        "variable %r used by op %s is neither fed, in scope, nor "
                        "produced earlier in the block (var=%s)" % (name, op, v)
                    )
            produced.update(n for n in op.output_arg_names if n != EMPTY_VAR_NAME)
        # fetches may be state too (e.g. fetch a param without running ops on it)
        for name in self.fetch_names:
            if name not in fed and name not in produced and name not in state_names:
                if scope.find_var(name) is not None:
                    state_names.append(name)
                else:
                    raise RuntimeError("fetch var %r has no value" % name)

        persistable = {
            name
            for name in state_names + list(produced)
            if block.has_var_recursive(name) and block._var_recursive(name).persistable
        }
        written = set()
        for op in self.ops:
            written.update(n for n in op.output_arg_names if n != EMPTY_VAR_NAME)
        # state already in scope and rewritten by the block → donated + returned
        self.mut_names = sorted(set(state_names) & written)
        self.ro_names = sorted(set(state_names) - written)
        # persistables created inside the block (e.g. startup initializers)
        self.created_persistables = sorted((persistable & produced) - set(state_names) - fed)

        # cross-check against the inplace_donation_plan pass when one rode in
        # on this program AND it analyzed this exact lowering (same scope,
        # feed, fetch, nothing unanalyzable). The plan is the verified source
        # of truth at this seam: divergence means a pass corrupted def-use or
        # the classifications drifted — fail loudly, not with silent
        # mis-donation (docs/passes.md).
        plan = getattr(program, "_donation_plan", None)
        if (
            plan
            and ops_override is None  # segments lower op SUBSETS the plan never saw
            and not plan.get("unknown")
            and plan.get("scope_uid") == scope._uid
            and plan.get("feed") == sorted(self.feed_names)
            and list(plan.get("fetch", ())) == list(self.fetch_names)
        ):
            if plan["mut"] != self.mut_names or plan["ro"] != self.ro_names:
                raise RuntimeError(
                    "inplace_donation_plan disagrees with the lowering's "
                    "state classification: plan mut=%s ro=%s vs lowered "
                    "mut=%s ro=%s — a pass likely corrupted def-use edges"
                    % (plan["mut"], plan["ro"], self.mut_names, self.ro_names)
                )

        ops_ = self.ops

        # declared feed-var dtypes: device-resident feeds arrive uncast (see
        # _as_feed_array) and are cast here at trace time, so the convert
        # fuses into the compiled step
        feed_want = {}
        for _n in self.feed_names:
            _v = block.vars.get(_n)
            if _v is None and block.has_var_recursive(_n):
                _v = block._var_recursive(_n)
            if _v is not None and getattr(_v, "dtype", None) is not None:
                feed_want[_n] = jnp.dtype(
                    jnp.bfloat16 if _v.dtype == "bfloat16" else np.dtype(_v.dtype)
                )
        self._feed_want = feed_want

        # ZeRO-1 active only when the mesh actually has >1 rank on the axis
        # (a dp=1 mesh degrades to the plain replicated path, same program)
        z1 = (
            zero1_axis
            if mesh is not None
            and zero1_axis
            and mesh.shape.get(zero1_axis, 1) > 1
            else None
        )
        self.zero1_axis = z1
        self._feed_ranks = dict(feed_ranks or {})

        # FLAGS_tensor_stats instrumentation pass (observability/opprof.py):
        # matched ops get output stats computed INSIDE the compiled step.
        # The flag value is part of the executor cache key, so toggling it
        # recompiles rather than serving a stale (un)instrumented block.
        # instrument=False for wrappers that would drop the side output
        # (_MultiStepBlock's scan body discards created).
        self._tstat_spec = ()
        self._tstat_traced = ()
        if instrument:
            pat = _flags_opprof()["tensor_stats"]
            if pat:
                from .observability import opprof as _opprof

                self._tstat_spec = _opprof.stats_spec(self.ops, pat)

        run = self._build_run(ops_, feed_want, mesh, z1)

        self.fn = run  # un-jitted lowering, reusable by __graft_entry__ et al.
        # donate the mutated-state pytree: params update in place on device
        if mesh is None:
            self.jitted = jax.jit(run, donate_argnums=(2,))
            self._feed_sharding = None
            self.zero1_state_names = []
            self._resolver = None
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            batch = NamedSharding(mesh, P(data_axes))
            repl = NamedSharding(mesh, P())
            self._feed_sharding = batch

            # the declarative sharding-rule engine (parallel/sharding_rules):
            # program-attached rules first, then BuildStrategy rules so the
            # caller wins ties under last-match. The resolver also layers the
            # legacy per-var sharding_spec attr and (below) the zero1 tier,
            # making it the block's single placement source of truth.
            from .parallel.sharding_rules import Resolver, ShardingRules

            combined = ShardingRules()
            combined.extend(getattr(program, "_sharding_rules", None))
            combined.extend(sharding_rules)

            def var_lookup(name):
                try:
                    return block._var_recursive(name)
                except KeyError:
                    return None

            resolver = Resolver(mesh, rules=combined, var_lookup=var_lookup)
            resolver.add_aliases(self.ops)
            self._resolver = resolver
            # dead-rule audit (analysis/sharding_dead_rules): a pattern that
            # matches neither a declared var nor a scope resident is a typo
            # silently replicating its target — surface it once per compile
            if len(combined):
                audit_names = set(scope.vars)
                for b in program.blocks:
                    audit_names.update(b.vars)
                resolver.audit(audit_names)

            # ZeRO-1: optimizer-state tensors live sharded 1/dp per rank —
            # the ÷dp state-memory/HBM win. Names come from the optimizer
            # ops' state input slots; only tensors whose leading dim divides
            # the axis shard (scalars like Beta*Pow stay replicated). State
            # whose PARAM has a rule/attr layout is excluded: the rule tier
            # (FSDP/TP) stores it in the param's spec instead.
            zero1_names = set()
            if z1 is not None:
                from .ops.core_ops import ZERO1_STATE_SLOTS
                from .parallel.collectives import zero1_shardable

                for op in self.ops:
                    for slot in ZERO1_STATE_SLOTS.get(op.type, ()):
                        for name in op.inputs.get(slot, ()):
                            val = scope.find_var(name)
                            if (
                                val is not None
                                and zero1_shardable(np.shape(val), mesh, z1)
                                and resolver.rule_spec(name, np.shape(val))
                                is None
                            ):
                                zero1_names.add(name)
            self.zero1_state_names = sorted(zero1_names)
            resolver.set_zero1(z1, zero1_names)

            def state_sharding(name):
                """Resolver verdict for one state tensor: explicit rules >
                accumulator alias > legacy shard_parameter attr > ZeRO-1
                state > replicated, pruned so axes the current mesh doesn't
                have degrade to replication (the same program runs on any
                mesh — e.g. distributed_embedding under a dp-only PE)."""
                val = scope.find_var(name)
                shape = np.shape(val) if val is not None else None
                return resolver.named_sharding(name, shape)

            # rank-0 feeds (scalars) cannot be batch-sharded — replicate them
            feed_ranks = feed_ranks or {}
            feed_sh = {
                n: (batch if feed_ranks.get(n, 1) else repl)
                for n in self.feed_names
            }
            ro_sh = {n: state_sharding(n) for n in self.ro_names}
            mut_sh = {n: state_sharding(n) for n in self.mut_names}
            # stashed for _MultiStepBlock, which reuses this block's analysis
            self._ro_sh, self._mut_sh = ro_sh, mut_sh
            # created dict's membership is only known at trace time (ops may
            # omit declared outputs), so its sharding is left to XLA (None)
            out_sh = (
                [repl] * len(self.fetch_names),
                {n: state_sharding(n) for n in self.mut_names},
                None,
                repl,
            )
            self.jitted = jax.jit(
                run,
                donate_argnums=(2,),
                in_shardings=(feed_sh, ro_sh, mut_sh, repl),
                out_shardings=out_sh,
            )

    def _build_run(self, ops_, feed_want, mesh, z1):
        """The block's lowering closure (overridden by _PipelinedBlock, which
        replaces the straight-line interpretation with the pp schedule)."""

        def run(feeds, ro_state, mut_state, rng_key):
            feeds = {
                n: (
                    v.astype(feed_want[n])
                    if n in feed_want and v.dtype != feed_want[n]
                    else v
                )
                for n, v in feeds.items()
            }
            env = {}
            env.update(ro_state)
            env.update(mut_state)
            env.update(feeds)
            ctx = registry.LowerCtx(
                rng_key, mesh=mesh, zero1_axis=z1,
                sharding=getattr(self, "_resolver", None),
            )
            registry.lower_ops(ctx, ops_, env)
            fetches = [env[n] for n in self.fetch_names]
            new_mut = {n: env[n] for n in self.mut_names}
            # an op may legally omit a declared output slot (lowering returns
            # None) — only bind names that actually materialized
            created = {n: env[n] for n in self.created_persistables if n in env}
            if self._tstat_spec:
                stats = self._trace_tensor_stats(env)
                if stats is not None:
                    # ride the created dict out of the jit: its sharding is
                    # already None (XLA's choice) and __call__ pops the key
                    # before it can reach the scope — ONE host sync per run,
                    # same trick as the nan-guard stacked reduce
                    from .observability.opprof import TENSOR_STATS_KEY

                    created[TENSOR_STATS_KEY] = stats
            return fetches, new_mut, created, ctx.key

        return run

    def _trace_tensor_stats(self, env):
        """FLAGS_tensor_stats: stats rows [mean, std, absmax, nonfinite] for
        every instrumented output present in the traced env, stacked into ONE
        [n, 4] f32 array. Runs AT TRACE TIME inside _build_run; the matched
        display names land on self (trace-time self mutation, the same
        pattern as _PipelinedBlock.stage_plan) so __call__ can label the
        host-side rows without retracing."""
        names, rows = [], []
        for display, var in self._tstat_spec:
            v = env.get(var)
            if v is None:
                continue
            a = jnp.asarray(v)
            if not jnp.issubdtype(a.dtype, jnp.floating):
                continue
            x = a.astype(jnp.float32)
            names.append(display)
            rows.append(
                jnp.stack([
                    x.mean(),
                    x.std(),
                    jnp.abs(x).max() if x.size else jnp.float32(0),
                    jnp.sum(~jnp.isfinite(x)).astype(jnp.float32),
                ])
            )
        self._tstat_traced = tuple(names)
        if not rows:
            return None
        return jnp.stack(rows)

    def __call__(self, scope, feed_arrays):
        ro = {n: scope.vars[n] for n in self.ro_names}
        mut = {n: scope.vars[n] for n in self.mut_names}
        fetches, new_mut, created, new_key = self.jitted(
            feed_arrays, ro, mut, scope.rng_key
        )
        stats = None
        if self._tstat_spec and isinstance(created, dict):
            from .observability.opprof import TENSOR_STATS_KEY

            stats = created.pop(TENSOR_STATS_KEY, None)
        scope.vars.update(new_mut)
        scope.vars.update(created)
        scope.rng_key = new_key
        if stats is not None:
            from .observability import opprof as _opprof

            try:
                # the leg's single host sync: one small [n, 4] transfer
                _opprof.record_tensor_stats(
                    self._tstat_traced, np.asarray(stats)
                )
            except Exception as e:
                if not getattr(_CompiledBlock, "_tstat_warned", False):
                    _CompiledBlock._tstat_warned = True
                    print(
                        "tensor_stats record failed (disabled for this "
                        "message): %r" % e, file=sys.stderr,
                    )
        return fetches


def aot_serve_lowering(program, feed_names, fetch_names, scope,
                       pass_pipeline="inference", return_state=False):
    """Donation-free forward lowering for ahead-of-time serving.

    The serving side (inference.export_compiled, serving.engine) needs the
    block's pure lowering WITHOUT the training executor's buffer-donation
    jit: a serving replica calls the same compiled variant from many request
    threads, so parameters must stay valid across calls. Returns
    (serve, ro, mut) where `serve(feeds, ro, mut) -> [fetches]` is a
    jit/export-able closure over the block's op lowerings, and ro/mut are the
    scope's read-only / block-rewritten persistables, passed as ARGUMENTS
    (not baked constants) so one artifact serves any parameter values of the
    same shapes. The scope's rng key is captured at trace time — inference
    programs are pruned of training-only stochastic ops by clone(for_test),
    so the key never advances.

    `return_state=True` is the decode-state mode (serving.generation): the
    closure becomes `serve(feeds, ro, mut) -> ([fetches], new_mut)` so a
    stateful caller (KV-cache pools) can thread the rewritten state dict to
    the next step and jit the wrapper with `donate_argnums=(2,)` — the
    single-shot default stays donation-free by construction.

    `pass_pipeline` (default: the "inference" preset, docs/passes.md) runs
    fold/DCE/fusion-tagging over the program before lowering; pass "" / None
    to lower the program verbatim.
    """
    program = _apply_pass_pipeline(
        program, scope, list(feed_names), list(fetch_names),
        pipeline=pass_pipeline if pass_pipeline else "off",
    )
    from .analysis import maybe_static_verify

    maybe_static_verify(
        program, list(feed_names), list(fetch_names), scope=scope,
        mode="serving", where="aot_serve",
    )
    block = program.global_block()
    compiled = _CompiledBlock(
        program, block, list(feed_names), list(fetch_names), scope,
        instrument=False,
    )
    ro = {n: scope.vars[n] for n in compiled.ro_names}
    mut = {n: scope.vars[n] for n in compiled.mut_names}
    rng_key = scope.rng_key

    if return_state:

        def serve(feeds, ro_, mut_):
            fetches, new_mut, _, _ = compiled.fn(feeds, ro_, mut_, rng_key)
            return fetches, new_mut

    else:

        def serve(feeds, ro_, mut_):
            # compiled.fn is the un-jitted lowering: (feeds, ro, mut, key) ->
            # (fetches, new_mut, created, key); serving wants fetches only
            fetches, _, _, _ = compiled.fn(feeds, ro_, mut_, rng_key)
            return fetches

    return serve, ro, mut


class _PipelinedBlock(_CompiledBlock):
    """Pipeline-parallel lowering of a whole training block over the mesh's
    'pp' axis (ParallelExecutor with MeshConfig(pp>1)).

    Where _CompiledBlock interprets the block straight-line under GSPMD,
    this block re-expresses it as a microbatch pipeline:

    1. ops split by op_role: forward (Forward/Loss) vs backward (skipped —
       the schedule differentiates the forward itself) vs optimizer
       (Optimize/LRSched, re-run verbatim after the pipeline so ZeRO-1,
       bf16 moments, lr schedules and clipping compose unchanged);
    2. the forward op list is cut into pp contiguous stages — explicit
       `device_guard("pp:k")` annotations win, otherwise
       parallel.partition balances analytic per-op roofline time + param
       bytes over the LEGAL cut points (every value crossing a cut must be
       microbatch-major so it can ride the packed boundary buffer);
    3. each stage's params stay canonical named tensors (replicated, the
       scope's layout) and enter the shard_map as a plain dict with P()
       specs — per-stage param pytrees are heterogeneous, and each rank's
       branch reads only its own stage's entries; inter-stage boundary
       activations are packed into a uniform [mb, K] f32 buffer;
    4. inside one shard_map over the full mesh, lax.switch on
       axis_index('pp') dispatches this rank's stage subgraph
       (registry.lower_ops on its op slice), and parallel.pipeline's
       GPipe or 1F1B engine runs the schedule; 'dp' keeps its meaning —
       each dp slice pipelines its own batch shard, grads pmean over dp;
    5. gradients come back as a dict (assembled across stages by the
       shard_map transpose / an explicit psum over 'pp'), are bound under
       the program's own `<param>@GRAD` names, and the block's optimizer
       ops run through registry.lower_ops exactly as in _CompiledBlock —
       same scope layout, so checkpoint save/resume, donation and the
       ZeRO-1 dp tier are untouched.

    Contracts/limits (all raised with guidance): the loss (and any fetched
    forward value) must land in the LAST stage; forward ops may not write
    persistable state (running stats); a parameter may be read by only one
    stage; fetched last-stage values are combined across microbatches by
    MEAN (exact for batch-mean losses/metrics).
    """

    def __init__(self, program, block, feed_names, fetch_names, scope,
                 mesh, feed_ranks=None, zero1_axis=None, sharding_rules=None,
                 loss_name=None, n_micro=None, schedule="gpipe"):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                "pipeline schedule must be 'gpipe' or '1f1b', got %r"
                % (schedule,)
            )
        if "pp" not in mesh.shape or mesh.shape["pp"] < 2:
            raise ValueError("_PipelinedBlock needs a mesh with pp >= 2")
        self._pp_opts = {
            "loss_name": loss_name, "n_micro": n_micro, "schedule": schedule,
        }
        self.stage_plan = None  # filled at first trace
        # instrument=False: the pp schedule's shard_map body has no place
        # for the straight-line stats side output (FLAGS_tensor_stats is a
        # single-device/dp diagnosis knob, docs/observability.md)
        super().__init__(
            program, block, feed_names, fetch_names, scope,
            mesh=mesh, feed_ranks=feed_ranks, zero1_axis=zero1_axis,
            sharding_rules=sharding_rules, instrument=False,
        )

    # packable boundary dtypes: everything is carried as f32 in the boundary
    # buffer via value-preserving casts (bf16/f16/bool/small ints are exact;
    # int32 is exact below 2^24 — larger ids crossing a cut need device_guard)
    _PACK_DTYPES = frozenset([
        "float32", "bfloat16", "float16", "bool",
        "int8", "uint8", "int16", "int32", "uint32",
    ])

    def _build_run(self, ops_, feed_want, mesh, z1):
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from .framework import GRAD_VAR_SUFFIX, OpRole
        from .parallel import partition as pp_partition
        from .parallel.collectives import SHARD_MAP_CHECK_KW, shard_map
        from .parallel.pipeline import pipeline_1f1b_spmd, pipeline_fwd_spmd

        pp = mesh.shape["pp"]
        opts = self._pp_opts
        self_ = self

        def role(op):
            return int(op.attrs.get(OpRole.OP_ROLE_KEY, 0))

        skip_mask = OpRole.Backward | OpRole.Optimize | OpRole.LRSched
        fwd_ops = [op for op in ops_ if not role(op) & skip_mask]
        opt_ops = [
            op for op in ops_
            if role(op) & (OpRole.Optimize | OpRole.LRSched)
        ]
        if not fwd_ops:
            raise RuntimeError("pipeline lowering: block has no forward ops")

        # trainable params = the optimizer section's Param slots
        param_set = set()
        for op in opt_ops:
            param_set.update(op.inputs.get("Param", ()))
        state_set = set(self.ro_names) | set(self.mut_names)
        param_set &= state_set

        fwd_written_state = sorted(
            {n for op in fwd_ops for n in op.output_arg_names} & state_set
        )
        if fwd_written_state:
            raise NotImplementedError(
                "pipeline-parallel lowering cannot thread forward-op state "
                "updates (%s) through the microbatch schedule; run these on "
                "a non-pp mesh" % (fwd_written_state,)
            )

        loss_name = opts["loss_name"]
        if loss_name is None:
            for op in fwd_ops:
                if role(op) & OpRole.Loss:
                    outs = [
                        n for n in op.output_arg_names if n != EMPTY_VAR_NAME
                    ]
                    if outs:
                        loss_name = outs[0]
                        break
        if loss_name is None:
            raise ValueError(
                "pipeline parallelism needs the loss: pass loss_name= to "
                "ParallelExecutor (no op in the block carries the Loss role)"
            )

        feed_ranks = self._feed_ranks
        fetch_names = list(self.fetch_names)

        def run(feeds, ro_state, mut_state, rng_key):
            feeds = {
                n: (
                    v.astype(feed_want[n])
                    if n in feed_want and v.dtype != feed_want[n]
                    else v
                )
                for n, v in feeds.items()
            }
            dp = mesh.shape.get("dp", 1)
            batch_feeds = {
                n: v for n, v in feeds.items()
                if np.ndim(v) > 0 and feed_ranks.get(n, np.ndim(v)) > 0
            }
            scalar_feeds = {
                n: v for n, v in feeds.items() if n not in batch_feeds
            }
            if not batch_feeds:
                raise ValueError(
                    "pipeline lowering needs at least one batch-major feed"
                )
            B = next(iter(batch_feeds.values())).shape[0]
            for n, v in batch_feeds.items():
                if v.shape[0] != B:
                    raise ValueError(
                        "batch feeds disagree on batch size: %r has %d, "
                        "expected %d" % (n, v.shape[0], B)
                    )
            if B % dp:
                raise ValueError(
                    "global batch %d not divisible by dp=%d" % (B, dp)
                )
            b_local = B // dp
            m = opts["n_micro"] or pp
            if b_local % m:
                raise ValueError(
                    "dp-local batch %d not divisible into %d microbatches "
                    "(set ExecutionStrategy.num_microbatches)" % (b_local, m)
                )
            mb = b_local // m

            state_env = {}
            state_env.update(ro_state)
            state_env.update(mut_state)

            # ---- abstract forward pass at microbatch scale: per-op output
            # avals drive cut legality, cost weights and packing layouts
            mb_feed_avals = {
                n: jax.ShapeDtypeStruct((mb,) + tuple(v.shape[1:]), v.dtype)
                for n, v in batch_feeds.items()
            }

            def absrun(bf, sf, st, key):
                env = {}
                env.update(st)
                env.update(sf)
                env.update(bf)
                ctx = registry.LowerCtx(key, mesh=None)
                recs = []
                for op in fwd_ops:
                    registry.lower_ops(ctx, [op], env)
                    recs.append({
                        n: env[n]
                        for n in op.output_arg_names
                        if n != EMPTY_VAR_NAME and n in env
                    })
                return recs

            recs = jax.eval_shape(
                absrun, mb_feed_avals, scalar_feeds, state_env, rng_key
            )

            producers = {}  # name -> [(op_idx, aval)] in program order
            for i, rec in enumerate(recs):
                for n, av in rec.items():
                    producers.setdefault(n, []).append((i, av))
            if loss_name not in producers:
                raise ValueError(
                    "loss %r is not produced by the forward ops" % loss_name
                )
            loss_idx = producers[loss_name][-1][0]
            n_ops = len(fwd_ops)

            # live values crossing each candidate cut k (between op k, k+1)
            crossing = [dict() for _ in range(max(n_ops - 1, 0))]
            for j, op in enumerate(fwd_ops):
                for n in op.input_arg_names:
                    if n == EMPTY_VAR_NAME:
                        continue
                    plist = [
                        (i, av) for (i, av) in producers.get(n, []) if i < j
                    ]
                    if not plist:
                        continue  # fed / state: available on every rank
                    i, av = plist[-1]
                    for k in range(i, min(j, n_ops - 1)):
                        crossing[k][n] = av

            def packable(av):
                return (
                    len(av.shape) >= 1
                    and av.shape[0] == mb
                    and str(jnp.dtype(av.dtype)) in self_._PACK_DTYPES
                )

            legal = [
                k for k in range(n_ops - 1)
                if k < loss_idx  # the loss must stay in the LAST stage
                and all(packable(av) for av in crossing[k].values())
            ]

            # ---- stage assignment: device_guard override, else balanced cut
            stages = pp_partition.stages_from_attrs(fwd_ops, pp)
            if stages is None:
                def aval_of(n, j):
                    plist = [
                        (i, av) for (i, av) in producers.get(n, []) if i < j
                    ]
                    if plist:
                        return plist[-1][1]
                    v = feeds.get(n)
                    if v is not None and n in mb_feed_avals:
                        return mb_feed_avals[n]
                    if v is None:
                        v = state_env.get(n)
                    if v is None:
                        return None
                    return jax.ShapeDtypeStruct(np.shape(v), v.dtype)

                weights = []
                for j, op in enumerate(fwd_ops):
                    in_avals = {
                        slot: [
                            aval_of(n, j)
                            for n in names if n != EMPTY_VAR_NAME
                        ]
                        for slot, names in op.inputs.items()
                    }
                    out_avals = {
                        slot: [recs[j].get(n) for n in names]
                        for slot, names in op.outputs.items()
                    }
                    weights.append(
                        pp_partition.analytic_op_time_us(
                            op.type, in_avals, out_avals
                        )
                    )
                # param read bytes, charged to the op of first use, so a
                # weight-heavy stage is as expensive as a FLOP-heavy one
                first_use = {}
                for j, op in enumerate(fwd_ops):
                    for n in op.input_arg_names:
                        if n in param_set and n not in first_use:
                            first_use[n] = j
                for n, j in first_use.items():
                    v = state_env[n]
                    pbytes = (
                        int(np.prod(np.shape(v)))
                        * np.dtype(v.dtype).itemsize
                    )
                    weights[j] += pbytes / 676.0e3
                stages = pp_partition.balanced_partition(weights, legal, pp)
            else:
                legal_set = set(legal)
                for k in range(n_ops - 1):
                    if stages[k + 1] != stages[k] and k not in legal_set:
                        bad = {
                            n: (tuple(av.shape), str(av.dtype))
                            for n, av in crossing[k].items()
                            if not packable(av)
                        }
                        raise ValueError(
                            "device_guard cut after op %d (%s) is illegal: "
                            "values crossing it are not microbatch-major or "
                            "the loss would leave the last stage: %s"
                            % (k, fwd_ops[k].type, bad or {"loss": loss_name})
                        )
            used = sorted(set(stages))
            if used != list(range(pp)):
                raise ValueError(
                    "pipeline partition produced stages %s for pp=%d; every "
                    "pp rank needs a non-empty stage (annotate with "
                    "device_guard('pp:k') or lower pp)" % (used, pp)
                )

            stage_of_op = stages
            param_stage = {}
            for j, op in enumerate(fwd_ops):
                for n in op.input_arg_names:
                    if n in param_set:
                        s0 = param_stage.setdefault(n, stage_of_op[j])
                        if s0 != stage_of_op[j]:
                            raise ValueError(
                                "parameter %r is read by pipeline stages %d "
                                "and %d; pin its consumers to one stage with "
                                "device_guard" % (n, s0, stage_of_op[j])
                            )

            stage_ops = [[] for _ in range(pp)]
            for op, s in zip(fwd_ops, stage_of_op):
                stage_ops[s].append(op)

            # boundary packing tables: cut s = after the last op of stage s
            cut_entries = []
            for s in range(pp - 1):
                k = max(j for j in range(n_ops) if stage_of_op[j] == s)
                ents = []
                for n in sorted(crossing[k]):
                    av = crossing[k][n]
                    w = int(np.prod(av.shape[1:])) if len(av.shape) > 1 else 1
                    ents.append(
                        (n, tuple(av.shape), jnp.dtype(av.dtype), w)
                    )
                cut_entries.append(ents)
            K = max([sum(e[3] for e in ents) for ents in cut_entries] + [1])

            # scalar outputs: loss first, then fetched last-stage values
            produced_fwd = set(producers)
            ext = set(feeds) | state_set
            scal_names = [loss_name] + [
                n for n in fetch_names
                if n != loss_name and n in produced_fwd and n not in ext
            ]
            scal_entries = []
            for n in scal_names:
                i, av = producers[n][-1]
                if stage_of_op[i] != pp - 1:
                    raise ValueError(
                        "the pp lowering can only fetch values computed in "
                        "the LAST pipeline stage; %r is computed in stage %d "
                        "— pin its ops with device_guard or drop the fetch"
                        % (n, stage_of_op[i])
                    )
                sz = int(np.prod(av.shape)) if av.shape else 1
                scal_entries.append(
                    (n, tuple(av.shape), jnp.dtype(av.dtype), sz)
                )
            if scal_entries[0][3] != 1:
                raise ValueError(
                    "loss %r must be scalar, got shape %s"
                    % (loss_name, scal_entries[0][1])
                )
            Ks = sum(e[3] for e in scal_entries)

            opt_out = {n for op in opt_ops for n in op.output_arg_names}
            grad_names_all = {n + GRAD_VAR_SUFFIX for n in param_set}
            for n in fetch_names:
                if (
                    n in ext or n in scal_names or n in opt_out
                    or n in grad_names_all
                ):
                    continue
                raise ValueError(
                    "fetch %r is a non-last-stage intermediate; under pp the "
                    "block returns only last-stage scalars, state, feeds and "
                    "optimizer outputs" % n
                )

            # per-stage parameter name lists (first-use order). The params
            # enter the shard_map REPLICATED (in_spec P()) and each rank's
            # switch branch reads only its own stage's entries — they are
            # jit arguments, so the manual-region entry is an identity.
            # (A packed [pp, S] buffer sharded P('pp') was tried first: a
            # jit-internal value entering a shard_map with a partial spec is
            # resharded by XLA as dynamic-update-slice + all-reduce over the
            # WHOLE mesh, which double-counts the dp replicas — scope params
            # are stored replicated anyway, so the dict costs no extra HBM.)
            stage_params = [[] for _ in range(pp)]
            for j, op in enumerate(fwd_ops):
                s = stage_of_op[j]
                for n in op.input_arg_names:
                    if n in param_set and n not in stage_params[s]:
                        stage_params[s].append(n)
            fwd_param_names = [n for ns in stage_params for n in ns]
            params_fwd = {n: state_env[n] for n in fwd_param_names}

            self_.stage_plan = {
                "schedule": opts["schedule"],
                "n_micro": int(m),
                "microbatch": int(mb),
                "op_stage": [int(s) for s in stage_of_op],
                "stages": [[op.type for op in ops] for ops in stage_ops],
                "stage_params": [list(ns) for ns in stage_params],
                "boundaries": [
                    [e[0] for e in ents] for ents in cut_entries
                ],
                "boundary_width": int(K),
            }

            # read-only state the forward consumes: replicated to all stages
            ro_for_fwd = {}
            for op in fwd_ops:
                for n in op.input_arg_names:
                    if (
                        n in state_set and n not in param_set
                        and n not in ro_for_fwd
                    ):
                        ro_for_fwd[n] = state_env[n]

            key_fwd, key_opt = jax.random.split(rng_key)

            def make_branches(feeds_micro, sfeeds, ro_vals, key):
                def make_branch(s):
                    in_ents = cut_entries[s - 1] if s > 0 else []
                    out_ents = cut_entries[s] if s < pp - 1 else []
                    s_ops = stage_ops[s]
                    s_params = stage_params[s]

                    def branch(params, bin_buf, mb_idx):
                        env = {}
                        env.update(sfeeds)
                        env.update(ro_vals)
                        for n in s_params:
                            env[n] = params[n]
                        for n, v in feeds_micro.items():
                            env[n] = lax.dynamic_index_in_dim(
                                v, mb_idx, axis=0, keepdims=False
                            )
                        off = 0
                        for (n, shp, dt, w) in in_ents:
                            env[n] = (
                                bin_buf[:, off:off + w].reshape(shp)
                                .astype(dt)
                            )
                            off += w
                        ctx = registry.LowerCtx(
                            jax.random.fold_in(
                                jax.random.fold_in(key, s), mb_idx
                            ),
                            mesh=None,
                        )
                        registry.lower_ops(ctx, s_ops, env)
                        if out_ents:
                            buf = jnp.concatenate([
                                env[n].reshape(mb, -1).astype(jnp.float32)
                                for (n, _, _, _) in out_ents
                            ], axis=1)
                            out = jnp.pad(
                                buf, ((0, 0), (0, K - buf.shape[1]))
                            )
                        else:
                            out = jnp.zeros((mb, K), jnp.float32)
                        if s == pp - 1:
                            scal = jnp.concatenate([
                                env[n].reshape(-1).astype(jnp.float32)
                                for (n, _, _, _) in scal_entries
                            ])
                        else:
                            scal = jnp.zeros((Ks,), jnp.float32)
                        return out, scal

                    return branch

                return [make_branch(s) for s in range(pp)]

            in_specs = (P(), P("dp"), P(), P(), P())

            if opts["schedule"] == "gpipe":
                def spmd_fwd(params, bfeeds_l, sfeeds, ro_vals, key):
                    feeds_micro = {
                        n: v.reshape((m, mb) + v.shape[1:])
                        for n, v in bfeeds_l.items()
                    }
                    branches = make_branches(feeds_micro, sfeeds, ro_vals, key)

                    def stage_f(bin_buf, mb_idx):
                        return lax.switch(
                            lax.axis_index("pp"), branches,
                            params, bin_buf, mb_idx,
                        )

                    scal = pipeline_fwd_spmd(
                        stage_f, m, (mb, K), Ks, axis_name="pp"
                    )
                    return lax.pmean(scal, "dp")

                sm = shard_map(
                    spmd_fwd, mesh=mesh, in_specs=in_specs, out_specs=P(),
                    **{SHARD_MAP_CHECK_KW: False},
                )

                def lossf(params):
                    scal = sm(
                        params, batch_feeds, scalar_feeds, ro_for_fwd,
                        key_fwd,
                    )
                    return scal[0], scal

                (_, scal), gdict = jax.value_and_grad(lossf, has_aux=True)(
                    params_fwd
                )
            else:  # 1f1b
                seed = jnp.zeros((Ks,), jnp.float32).at[0].set(1.0 / m)

                def spmd_both(params, bfeeds_l, sfeeds, ro_vals, key):
                    feeds_micro = {
                        n: v.reshape((m, mb) + v.shape[1:])
                        for n, v in bfeeds_l.items()
                    }
                    branches = make_branches(feeds_micro, sfeeds, ro_vals, key)

                    def stage_f(p, bin_buf, mb_idx):
                        return lax.switch(
                            lax.axis_index("pp"), branches,
                            p, bin_buf, mb_idx,
                        )

                    scal, gacc = pipeline_1f1b_spmd(
                        stage_f, params, m, (mb, K), seed, axis_name="pp"
                    )
                    # each rank's vjp is nonzero only for its own stage's
                    # params: psum over 'pp' assembles the full dict, pmean
                    # over 'dp' matches GPipe's dp-mean gradient
                    gacc = jax.tree_util.tree_map(
                        lambda g: lax.pmean(lax.psum(g, "pp"), "dp"), gacc
                    )
                    return lax.pmean(scal, "dp"), gacc

                sm = shard_map(
                    spmd_both, mesh=mesh, in_specs=in_specs,
                    out_specs=(P(), P()),
                    **{SHARD_MAP_CHECK_KW: False},
                )
                scal, gdict = sm(
                    params_fwd, batch_feeds, scalar_feeds, ro_for_fwd,
                    key_fwd,
                )

            # ---- bind grads under the program's own @GRAD names and run
            # the block's optimizer section verbatim
            env = {}
            env.update(ro_state)
            env.update(mut_state)
            env.update(feeds)
            for n in fwd_param_names:
                env[n + GRAD_VAR_SUFFIX] = gdict[n].astype(
                    state_env[n].dtype
                )
            for n in param_set:
                gname = n + GRAD_VAR_SUFFIX
                if gname not in env:  # param unused by the forward: zero grad
                    v = state_env[n]
                    env[gname] = jnp.zeros(np.shape(v), v.dtype)
            off = 0
            for (n, shp, dt, sz) in scal_entries:
                env[n] = scal[off:off + sz].reshape(shp).astype(dt)
                off += sz
            ctx = registry.LowerCtx(
                key_opt, mesh=mesh, zero1_axis=z1,
                sharding=getattr(self_, "_resolver", None),
            )
            registry.lower_ops(ctx, opt_ops, env)
            fetches = [env[n] for n in fetch_names]
            new_mut = {n: env[n] for n in self_.mut_names}
            created = {
                n: env[n] for n in self_.created_persistables if n in env
            }
            return fetches, new_mut, created, ctx.key

        return run


class _MultiStepBlock:
    """k iterations of a training block compiled into ONE XLA call.

    `jax.lax.scan` drives the block's lowering over a stacked feed (leading
    axis k), threading the donated mutated-state pytree (params, optimizer
    state, running stats) and the PRNG key through the loop carry. Per-step
    fetches come back stacked [k, ...].

    Reference analog: scope_buffered_ssa_graph_executor.h:37
    `num_iteration_per_drop_scope` — the reference amortizes per-iteration
    host work (scope GC) over k iterations without leaving the executor. Here
    the amortized cost is the dispatch itself: a training step carries ~480
    state buffers per call, which costs ~3 ms of host work per step on a
    tunneled chip (ROADMAP "Executor arg packing" probe); one multi-step call
    pays that once for k steps, so wall-clock tracks device-busy time without
    hand-packing state into per-dtype arenas.

    RNG equivalence: the scan body threads the key exactly as k sequential
    Executor.run calls would (registry.lower_ops splits per stochastic op),
    so dropout-bearing programs produce bitwise-identical trajectories either
    way — asserted by tests/test_multistep.py.
    """

    def __init__(self, program, block, feed_names, fetch_names, scope,
                 steps_per_run, mesh=None, data_axes=("dp",), feed_ranks=None,
                 zero1_axis=None, sharding_rules=None):
        if steps_per_run < 1:
            raise ValueError("steps_per_run must be >= 1")
        self.steps_per_run = steps_per_run
        # reuse _CompiledBlock's whole analysis (state split, shardings) and
        # its raw lowering closure; its own .jitted is lazy and never compiled
        # instrument=False: the scan body discards the created dict, which
        # is the stats side channel (FLAGS_tensor_stats needs
        # steps_per_run=1 — its one-sync-per-RUN contract is per run anyway)
        inner = _CompiledBlock(
            program, block, feed_names, fetch_names, scope,
            mesh=mesh, data_axes=data_axes, feed_ranks=feed_ranks,
            zero1_axis=zero1_axis, sharding_rules=sharding_rules,
            instrument=False,
        )
        if inner.created_persistables:
            raise RuntimeError(
                "steps_per_run>1 requires a block that creates no new "
                "persistables (run the startup program separately first); "
                "this block creates %s" % inner.created_persistables
            )
        self._inner = inner
        self.feed_names = inner.feed_names
        self.fetch_names = inner.fetch_names
        self.ro_names = inner.ro_names
        self.mut_names = inner.mut_names
        self.zero1_axis = inner.zero1_axis
        self.zero1_state_names = inner.zero1_state_names
        self._feed_sharding = None

        def run_k(stacked_feeds, ro_state, mut_state, rng_key):
            def body(carry, feeds):
                mut, key = carry
                fetches, new_mut, _created, new_key = inner.fn(
                    feeds, ro_state, mut, key
                )
                return (new_mut, new_key), fetches

            (mut, key), stacked_fetches = jax.lax.scan(
                body, (mut_state, rng_key), stacked_feeds, length=steps_per_run
            )
            return stacked_fetches, mut, key

        if mesh is None:
            self.jitted = jax.jit(run_k, donate_argnums=(2,))
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            # stacked feeds: scan axis unsharded, batch dim on the data axes
            batch = NamedSharding(mesh, P(None, data_axes))
            self._feed_sharding = batch
            feed_ranks = feed_ranks or {}
            feed_sh = {
                n: (batch if feed_ranks.get(n, 1) else repl)
                for n in self.feed_names
            }
            out_sh = ([repl] * len(self.fetch_names), inner._mut_sh, repl)
            self.jitted = jax.jit(
                run_k,
                donate_argnums=(2,),
                in_shardings=(feed_sh, inner._ro_sh, inner._mut_sh, repl),
                out_shardings=out_sh,
            )

    def __call__(self, scope, stacked_feed_arrays):
        ro = {n: scope.vars[n] for n in self.ro_names}
        mut = {n: scope.vars[n] for n in self.mut_names}
        stacked_fetches, new_mut, new_key = self.jitted(
            stacked_feed_arrays, ro, mut, scope.rng_key
        )
        scope.vars.update(new_mut)
        scope.rng_key = new_key
        return stacked_fetches


def _pull_reader_steps(readers, steps_per_run):
    """Pull up to k staged batches from started py_readers and stack them.
    If the epoch ends mid-pull, the completed steps are NOT discarded: the
    call proceeds as a shorter multi-step run (the sequential path would
    have trained on them before raising EOF) and EOFException surfaces on
    the NEXT run, once nothing is left. Returns (stacked_feed, k_actual);
    the feed is ALWAYS stacked [k, ...] — even a 1-batch tail keeps the
    multi-step fetch contract (fetches come back [k, ...])."""
    from .py_reader import EOFException

    step_feeds = []
    try:
        for _ in range(steps_per_run):
            d = {}
            pulled = []  # (reader, batch) of this incomplete step
            for rd in readers:
                b = rd.next_batch()
                pulled.append((rd, b))
                d.update(b)
            pulled = None  # step completed
            step_feeds.append(d)
    except EOFException:
        # one reader of the group ended mid-step: the sibling batches
        # already pulled for the INCOMPLETE step go back to their readers
        # (they were never trained on), and the whole group defers the EOF
        if pulled:
            for rd, b in pulled:
                rd.push_back(b)
        if not step_feeds:
            raise
        # tail consumed now; surface the EOF on the NEXT run
        for rd in readers:
            rd._eof_deferred = True
    return _stack_feed_steps(step_feeds), len(step_feeds)


def _started_readers(program):
    """Started py_readers of the program; raises the EOFException a previous
    partial multi-step pull deferred (its tail batches were trained on, so
    the epoch end belongs to THIS call). The program's readers are treated
    as a UNIT: a deferred EOF on any of them ends the epoch for the group —
    proceeding with the remaining readers would silently feed steps missing
    the exhausted reader's slots."""
    from .py_reader import EOFException

    readers, deferred = [], False
    for rd in getattr(program, "_py_readers", []):
        if getattr(rd, "_eof_deferred", False):
            rd._eof_deferred = False
            deferred = True
        elif rd.started:
            readers.append(rd)
    if deferred:
        raise EOFException(
            "reader exhausted (tail consumed by the previous multi-step run)"
        )
    return readers


def _resolve_reader_feed(program, steps_per_run):
    """Shared Executor/ParallelExecutor path for feed=None: pull from the
    program's started py_readers — k batches stacked for a multi-step run
    (force_multi keeps the [k, ...] fetch contract even for a 1-batch epoch
    tail), one batch otherwise. Returns (feed, steps_per_run, force_multi)."""
    readers = _started_readers(program)
    if steps_per_run > 1 and readers:
        feed, k = _pull_reader_steps(readers, steps_per_run)
        return feed, k, True
    feed = {}
    for rd in readers:
        feed.update(rd.next_batch())
    return feed, steps_per_run, False


def _stack_feed_steps(feed_list):
    """List of k per-step feed dicts -> one dict of stacked arrays
    (leading axis k). Device-resident values stack on device."""
    if not feed_list:
        raise ValueError("empty feed list")
    names = set(feed_list[0])
    for d in feed_list[1:]:
        if set(d) != names:
            raise ValueError(
                "per-step feeds must share the same names; got %s vs %s"
                % (sorted(names), sorted(d))
            )
    out = {}
    for name in names:
        vals = [d[name] for d in feed_list]
        if any(isinstance(v, jax.Array) for v in vals):
            out[name] = jnp.stack([jnp.asarray(v) for v in vals])
        else:
            out[name] = np.stack([np.asarray(v) for v in vals])
    return out


def _all_finite(values):
    """True iff every floating array in `values` is fully finite. One stacked
    device reduce + a single host sync (same trick as FLAGS_check_nan_inf)."""
    flags_ = [
        jnp.isfinite(a).all()
        for v in values
        for a in (jnp.asarray(v),)
        if jnp.issubdtype(a.dtype, jnp.floating)
    ]
    return (not flags_) or bool(jnp.stack(flags_).all())


def _poison_nan(feed_arrays):
    """`nan_grad` fault payload: overwrite the first floating feed with NaN,
    which propagates through loss -> grads -> every updated parameter — the
    realistic shape of a bad-numerics step. Returns (feed, poison_after);
    poison_after=True means no float feed existed (int-only models), so the
    caller poisons the updated state after the run instead."""
    out = dict(feed_arrays)
    for name in sorted(out):
        a = out[name]
        if not jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
            continue
        if isinstance(a, jax.Array):
            out[name] = jnp.full_like(a, jnp.nan)
        else:
            out[name] = np.full_like(np.asarray(a), np.nan)
        return out, False
    return out, True


def _poison_scope_state(scope, mut_names):
    """Fallback nan_grad payload: NaN the first floating mutated persistable
    (post-run), so the guard still sees a poisoned step."""
    for name in sorted(mut_names):
        v = scope.vars.get(name)
        if v is not None:
            a = jnp.asarray(v)
            if jnp.issubdtype(a.dtype, jnp.floating):
                scope.vars[name] = jnp.full_like(a, jnp.nan)
                return


class _SegmentedBlock:
    """A block containing host ops (RPC send/recv, listen_and_serv — the
    reference's non-kernel OperatorBase ops), executed as alternating XLA
    segments and host calls.

    Reference analog: the reference's per-op interpreter runs host ops
    in-line with kernels (executor.cc:389-396); here the block is partitioned
    AT host-op boundaries, each maximal device run is one jitted XLA segment
    (same _CompiledBlock machinery), and values cross segments through the
    Scope. Segments compile lazily at first execution so vars produced by
    earlier host ops (e.g. recv outputs) are in scope by then."""

    def __init__(self, program, block, feed_names, fetch_names):
        self.program = program
        self.block = block
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        # partition: [("xla", [ops]) | ("host", op)]
        self.segments = []
        cur = []
        for op in block.ops:
            opdef = registry.get(op.type)
            if opdef.is_host:
                if cur:
                    self.segments.append(("xla", cur))
                    cur = []
                self.segments.append(("host", op))
            else:
                cur.append(op)
        if cur:
            self.segments.append(("xla", cur))

        # per-xla-segment exports: produced names consumed by later segments,
        # host ops, or the final fetch list — these leave the jit via fetches
        # and land in the scope (persistable mutations are handled by
        # _CompiledBlock's donated-state path independently).
        later_consumed = set(self.fetch_names)
        self._exports = [None] * len(self.segments)
        for i in range(len(self.segments) - 1, -1, -1):
            kind, payload = self.segments[i]
            if kind == "xla":
                produced = set()
                for op in payload:
                    produced.update(op.output_arg_names)
                self._exports[i] = sorted(produced & later_consumed)
                for op in payload:
                    later_consumed.update(op.input_arg_names)
            else:
                later_consumed.update(payload.input_arg_names)
        self._compiled = [None] * len(self.segments)
        # persistables any op writes — lets FLAGS_check_nan_inf scan updated
        # state on segmented (host-op) programs too, like _CompiledBlock
        self.mut_names = sorted(
            {
                n
                for op in block.ops
                for n in op.output_arg_names
                if n != registry.EMPTY_VAR_NAME
                and block.has_var_recursive(n)
                and block._var_recursive(n).persistable
            }
        )

    def __call__(self, scope, feed_arrays):
        # feeds enter the scope directly (segments read them as state), so
        # declared-dtype casts must happen eagerly here — the fused
        # trace-time cast only covers _CompiledBlock-run feeds
        feed_arrays = _eager_cast_feeds(self.block, feed_arrays)
        for name, value in feed_arrays.items():
            scope.set_var(
                name, value if isinstance(value, jax.Array) else jnp.asarray(value)
            )
        from . import profiler as _prof

        for i, (kind, payload) in enumerate(self.segments):
            if kind == "host":
                with _prof.RecordEvent("host_op/%s" % payload.type):
                    registry.get(payload.type).host_fn(payload, scope)
                continue
            if not payload:
                continue
            compiled = self._compiled[i]
            if compiled is None:
                with _prof.RecordEvent("compile/segment_%d" % i):
                    compiled = _CompiledBlock(
                        self.program,
                        self.block,
                        [],
                        self._exports[i],
                        scope,
                        ops_override=payload,
                    )
                self._compiled[i] = compiled
            with _prof.RecordEvent("xla_segment_%d" % i):
                vals = compiled(scope, {})
                if _prof.is_profiling():
                    # XLA dispatch is async; block so the event spans compute
                    # (reference FLAGS_benchmark dev_ctx->Wait, operator.cc:769)
                    vals = [jax.block_until_ready(v) for v in vals]
            for name, val in zip(self._exports[i], vals):
                scope.set_var(name, val)
        return [scope.find_var(n) for n in self.fetch_names]


class Executor:
    """Drop-in for fluid.Executor (reference python/paddle/fluid/executor.py:256).

    `place` is accepted for API compatibility (fluid.CPUPlace()/CUDAPlace(0)/
    TPUPlace()); actual placement follows jax's default device unless the place
    pins one.
    """

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        # monotonically counts run() calls — the "step index" the
        # check_nan_inf / nan-provenance reports cite (the telemetry step
        # counter only advances when telemetry is on)
        self._run_seq = 0

    def close(self):
        """Reference Executor::Close (executor.cc:111-119): notify pservers
        this trainer is done (SendComplete), letting their sync loops exit."""
        self._cache.clear()
        from .distributed.rpc import RPCClient

        client = RPCClient._instance
        if client is not None:
            for ep in list(client._socks):
                client.send_complete(ep)
            client.close()

    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
        steps_per_run=1,
    ):
        """steps_per_run > 1 compiles k iterations into ONE XLA call
        (_MultiStepBlock): `feed` is then either a list of k per-step dicts
        or a dict of stacked arrays with leading axis k, and each fetch comes
        back stacked [k, ...]. With no feed, k staged batches are pulled from
        the program's started py_readers."""
        if program is None:
            program = framework.default_main_program()
        # elastic step-deadline watchdog: every run entry is a progress beat
        # (resilience/elastic.py heartbeat — one list probe when no
        # Supervisor is active)
        _elastic_heartbeat()
        # telemetry (observability.stepstats): t0 brackets the WHOLE run —
        # reader pull, dispatch, and the fetch conversion (which is where
        # the device sync lands under return_numpy / FLAGS_benchmark)
        _obs, _obs_t0 = _telemetry_begin()
        self._run_seq += 1
        # force_multi: a reader pull that returned a 1-batch epoch tail still
        # runs through _MultiStepBlock so fetches keep their [k, ...] axis
        force_multi = False
        if feed is None:
            # pull staged batches from started py_readers (reference read_op
            # popping the LoDTensorBlockingQueue); raises EOFException at end
            feed, steps_per_run, force_multi = _resolve_reader_feed(
                program, steps_per_run
            )
        elif isinstance(feed, (list, tuple)):
            if steps_per_run == 1:
                steps_per_run = len(feed)
            if len(feed) != steps_per_run:
                raise ValueError(
                    "feed list has %d entries but steps_per_run=%d"
                    % (len(feed), steps_per_run)
                )
            if steps_per_run == 1:
                feed = dict(feed[0])  # single step: no stacking, no scan
            else:
                feed = _stack_feed_steps(list(feed))
        if fetch_list is None:
            fetch_list = []
        scope = scope or global_scope()
        # the lazy rng_key property covers the fresh-scope case from the
        # scope's own seed; only an explicit program.random_seed overrides it
        if program.random_seed and not getattr(scope, "_seeded", False):
            scope.rng_key = jax.random.key(program.random_seed)
            scope._seeded = True

        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]
        # graph-pass choke point (docs/passes.md): FLAGS_pass_pipeline rewrites
        # the program here, before any lowering below sees it. Reader/feed
        # resolution above ran on the ORIGINAL program (its _py_readers);
        # everything from here down uses the (memoized) transformed one.
        program = _apply_pass_pipeline(
            program, scope, list(feed.keys()), fetch_names
        )
        block = program.global_block()

        feed_arrays = {}
        for name, value in feed.items():
            var = block.vars.get(name)
            feed_arrays[name] = _as_feed_array(value, var)

        _opf = _flags_opprof()
        key = (
            program._uid,
            program._version,
            tuple(sorted((n, a.shape, str(a.dtype)) for n, a in feed_arrays.items())),
            tuple(fetch_names),
            scope._uid,
            steps_per_run,
            # only the k==1 case needs disambiguating from single-step; for
            # k>1 an explicit stacked feed and a reader pull share the
            # compiled scan
            force_multi and steps_per_run == 1,
            # toggling FLAGS_tensor_stats must recompile, not serve a stale
            # (un)instrumented block
            _opf["tensor_stats"],
        )
        from . import profiler as _prof

        is_multi = steps_per_run > 1 or force_multi
        if _prof.is_profiling() and _flags_profile_ops() and not is_multi:
            # per-op attribution mode: never cached (diagnosis path); falls
            # through to the shared nan-check/return tail below. Multi-step
            # runs skip it — unfused per-op eager execution is the opposite
            # of what steps_per_run exists to measure.
            compiled = _PerOpProfiledBlock(
                program, block, list(feed_arrays.keys()), fetch_names
            )
            with _prof.RecordEvent("run/block0"):
                fetches = compiled(scope, _eager_cast_feeds(block, feed_arrays))
            return self._finish_run(
                compiled, scope, fetch_names, fetches, return_numpy,
                step=self._run_seq,
            )

        compiled = self._cache.get(key) if use_program_cache else None
        _obs_cache_hit = compiled is not None
        if compiled is None:
            # FLAGS_static_verify (docs/static_analysis.md): prove the program
            # against the fluidlint suite before paying for the trace below
            from .analysis import maybe_static_verify

            maybe_static_verify(
                program, list(feed_arrays.keys()), fetch_names, scope=scope,
                mode="inference" if getattr(program, "_is_test", False)
                else "training",
                where="executor",
            )
            has_host = any(
                registry.is_registered(op.type) and registry.get(op.type).is_host
                for op in block.ops
            )
            with _prof.RecordEvent("prepare/block0"):
                if has_host:
                    if is_multi:
                        raise RuntimeError(
                            "steps_per_run>1 cannot span host ops (send/recv/"
                            "listen_and_serv): the k-step scan is one XLA "
                            "computation with no host re-entry"
                        )
                    compiled = _SegmentedBlock(
                        program, block, list(feed_arrays.keys()), fetch_names
                    )
                elif is_multi:
                    compiled = _MultiStepBlock(
                        program, block, list(feed_arrays.keys()), fetch_names,
                        scope, steps_per_run,
                    )
                else:
                    compiled = _CompiledBlock(
                        program, block, list(feed_arrays.keys()), fetch_names, scope
                    )
            if use_program_cache:
                self._cache[key] = compiled

        from . import flags as _flags

        # --- resilience: NaN injection + step guard (docs/resilience.md) ---
        # only runs that mutate persistable state count as training steps;
        # startup/eval programs pass through untouched
        mut_names = getattr(compiled, "mut_names", ()) or ()
        poison_after = False
        guard_snapshot = None
        if mut_names:
            from .resilience import faults as _faults

            if _faults.fires("nan_grad"):
                feed_arrays, poison_after = _poison_nan(feed_arrays)
            if _flags.get_flags("resilience_nan_guard")["resilience_nan_guard"]:
                # host copies taken BEFORE the step: the donated in-place
                # update invalidates the old device buffers, so these copies
                # are the only way back when the step turns out poisoned.
                # np.array on top of the __array__ view — on the CPU backend
                # np.asarray of a jax array is zero-copy, so the donated
                # update would rewrite the "snapshot" underneath us
                guard_snapshot = {
                    n: np.array(np.asarray(scope.vars[n]))
                    for n in mut_names
                    if scope.vars.get(n) is not None
                }

        # pre-step rng key: scope.rng_key is consumed by the run; the
        # provenance replay must start from the same key to reproduce the
        # step's randomness op for op
        pre_key = scope.rng_key if _opf["nan_provenance"] else None

        with _prof.RecordEvent("run/block0"):
            fetches = compiled(scope, feed_arrays)
            if _prof.is_profiling() or _flags.get_flags("benchmark")["benchmark"]:
                # reference FLAGS_benchmark: wait so host timing is real step
                # time (operator.cc:769 dev_ctx->Wait)
                fetches = [jax.block_until_ready(f) for f in fetches]

        nan_ok = False
        if poison_after:
            # integer-only feeds can't carry the injected NaN through the
            # loss; poison the updated state directly instead
            _poison_scope_state(scope, mut_names)
        if guard_snapshot is not None:
            watched = list(fetches) + [
                scope.vars[n] for n in mut_names if scope.vars.get(n) is not None
            ]
            if not _all_finite(watched):
                from .observability import flightrec as _flightrec

                _flightrec.trigger("nan_guard", step=self._run_seq)
                if _opf["nan_provenance"]:
                    # localize BEFORE the rollback erases the poisoned state;
                    # the replay itself runs against the pre-step snapshot
                    _localize_nan(
                        compiled, scope, feed_arrays, pre_key,
                        "resilience_nan_guard", step=self._run_seq,
                        mut_override=guard_snapshot,
                    )
                nan_ok = self._skip_nan_step(scope, guard_snapshot)
        # correlation seed for profiler.device_op_profile: the block + feed
        # AVALS of the latest run (abstract shapes only — storing the
        # concrete arrays would pin a whole batch of device memory), from
        # which compiled_hlo() lowers the metadata-carrying HLO text
        if isinstance(compiled, (_CompiledBlock, _MultiStepBlock)):
            # weakref: _last_run must not keep a dropped scope's parameters
            # alive in device memory
            self._last_run = (
                compiled,
                weakref.ref(scope),
                {
                    n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for n, a in feed_arrays.items()
                },
            )
        result = self._finish_run(
            compiled, scope, fetch_names, fetches, return_numpy, nan_ok=nan_ok,
            step=self._run_seq, feed_arrays=feed_arrays, pre_key=pre_key,
        )
        if _obs is not None:
            _telemetry_record(
                _obs, _obs_t0, compiled, _obs_cache_hit, nan_ok,
                steps_per_run if is_multi else 1, result, return_numpy,
            )
        return result

    def compiled_hlo(self):
        """Post-optimization HLO text of the most recently run compiled
        block. Every instruction carries op_name=".../<op type>/..." metadata
        (registry.lower_ops wraps each lowering in jax.named_scope), so
        profiler.device_op_profile can fold an xla_trace's per-HLO device
        timings back onto framework op types — the TPU analog of the
        reference's CUPTI kernel→op correlation (platform/device_tracer.cc).
        The compile is served from the backend's compilation cache after a
        run, so this does not recompile."""
        last = getattr(self, "_last_run", None)
        if last is None:
            raise RuntimeError("compiled_hlo() needs a prior Executor.run")
        compiled, scope_ref, feed_avals = last
        scope = scope_ref()
        if scope is None:
            raise RuntimeError(
                "compiled_hlo(): the scope of the last run no longer exists"
            )
        ro = {n: scope.vars[n] for n in compiled.ro_names}
        mut = {n: scope.vars[n] for n in compiled.mut_names}
        lowered = compiled.jitted.lower(feed_avals, ro, mut, scope.rng_key)
        return lowered.compile().as_text()

    def _skip_nan_step(self, scope, snapshot):
        """The NaN/Inf step guard tripped: roll the mutated persistables back
        to their pre-step values, decay any loss-scale / learning-rate vars
        (graceful degradation — repeated NaNs usually mean the scale or lr is
        too hot), and count the event. The run then returns the poisoned
        fetches to the caller, but the MODEL state is as if the step never
        happened, so training continues."""
        import jax.numpy as jnp

        from . import flags as _flags
        from .resilience import health as _health

        for name, saved in snapshot.items():
            scope.vars[name] = jnp.asarray(saved)
        decay = float(
            _flags.get_flags("resilience_lr_decay")["resilience_lr_decay"]
        )
        decayed = 0
        for name, val in list(scope.vars.items()):
            base = name.rsplit("/", 1)[-1]
            if val is not None and (
                base.startswith("learning_rate") or "loss_scaling" in base
            ):
                scope.vars[name] = jnp.asarray(val) * decay
                decayed += 1
        if decayed:
            _health.incr("lr_decays", decayed)
        _health.incr("nan_steps_skipped")
        return True

    @staticmethod
    def _finish_run(compiled, scope, fetch_names, fetches, return_numpy,
                    nan_ok=False, step=None, feed_arrays=None, pre_key=None):
        """Shared run tail: FLAGS_check_nan_inf scan + numpy conversion.
        nan_ok: the resilience guard already handled this step's NaNs (state
        rolled back) — don't let the check_nan_inf scan abort over them.
        step/feed_arrays/pre_key feed the error report: the run index for the
        message, and (under FLAGS_nan_provenance) the replay inputs for
        first-bad-op localization."""
        from . import flags as _flags

        if not nan_ok and _flags.get_flags("check_nan_inf")["check_nan_inf"]:
            # reference FLAGS_check_nan_inf (operator.cc:778): finiteness
            # reduces ON DEVICE into one stacked scalar (a single host sync
            # per step); only when it trips does the per-var rescan run to
            # name the culprit
            watched = list(zip(fetch_names, fetches)) + [
                (n, scope.vars[n])
                for n in getattr(compiled, "mut_names", ())
                if n in scope.vars
            ]
            finite_flags = [
                jnp.isfinite(a).all()
                for _, v in watched
                for a in (jnp.asarray(v),)
                if jnp.issubdtype(a.dtype, jnp.floating)
            ]
            if finite_flags and not bool(jnp.stack(finite_flags).all()):
                for name, val in watched:
                    arr = jnp.asarray(val)
                    if jnp.issubdtype(arr.dtype, jnp.floating) and not bool(
                        jnp.isfinite(arr).all()
                    ):
                        msg = (
                            "check_nan_inf: variable %r contains NaN/Inf"
                            % name
                        )
                        writer = _last_writer(compiled, name)
                        if writer is not None:
                            msg += ", last written by op %s" % writer
                        if step is not None:
                            msg += " (run step %d)" % step
                        if (
                            feed_arrays is not None
                            and _flags_opprof()["nan_provenance"]
                        ):
                            # best-effort: the donated step already advanced
                            # the state, so this replays against POST-step
                            # values — right op for a feed/activation NaN,
                            # approximate for one born inside the update
                            prov = _localize_nan(
                                compiled, scope, feed_arrays, pre_key,
                                "check_nan_inf", step=step,
                            )
                            if prov is not None:
                                msg += (
                                    "; first non-finite output at op #%s %s"
                                    % (prov.get("op_index"), prov.get("op"))
                                )
                        raise FloatingPointError(msg)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches


class _PerOpProfiledBlock:
    """Op-by-op EAGER execution with a RecordEvent + device sync per op —
    the reference's per-op profiler tables (platform/profiler wraps every
    op->Run, operator.cc:157). Fusion is deliberately lost: this exists to
    attribute time per op type under FLAGS_profile_ops, not to train fast."""

    def __init__(self, program, block, feed_names, fetch_names):
        self.block = block
        self.fetch_names = list(fetch_names)
        unknown = sorted(
            {op.type for op in block.ops if not registry.is_registered(op.type)}
        )
        if unknown:
            # same diagnosis-quality error as the jitted path
            raise NotImplementedError("ops without lowering: %s" % unknown)
        self.ops = [
            op for op in block.ops if not registry.get(op.type).skip_exec
        ]
        # nan-check contract shared with _CompiledBlock/_SegmentedBlock
        self.mut_names = sorted(
            {
                n
                for op in self.ops
                for n in op.output_arg_names
                if n != registry.EMPTY_VAR_NAME
                and block.has_var_recursive(n)
                and block._var_recursive(n).persistable
            }
        )

    def __call__(self, scope, feed_arrays):
        from . import profiler as _prof

        env = dict(scope.vars)
        for name, value in feed_arrays.items():
            env[name] = value if isinstance(value, jax.Array) else jnp.asarray(value)
        ctx = registry.LowerCtx(scope.rng_key)
        from .observability import opprof as _opprof

        for op in self.ops:
            opdef = registry.get(op.type)
            # display form ("<type>:<first output>") so the host-events table
            # distinguishes op INSTANCES like the xplane leg does
            with _prof.RecordEvent("op/%s" % _opprof.op_display_name(op)):
                if opdef.is_host:
                    # host ops see a scratch scope view so env temporaries
                    # never leak into the real scope
                    before = set(scope.vars)
                    scope.vars.update(env)
                    opdef.host_fn(op, scope)
                    env.update(scope.vars)
                    for name in set(scope.vars) - before:
                        if name not in self.mut_names:
                            scope.vars.pop(name, None)
                    continue
                # the shared interpreter body (one op at a time), then sync
                # this op's outputs so the event brackets its device time
                registry.lower_ops(ctx, [op], env)
                for name in op.output_arg_names:
                    val = env.get(name)
                    if isinstance(val, jax.Array):
                        env[name] = jax.block_until_ready(val)
        scope.rng_key = ctx.key
        # persist block-written persistables like the jitted path does
        for name in self.mut_names:
            if name in env:
                scope.vars[name] = env[name]
        return [env[n] for n in self.fetch_names]
