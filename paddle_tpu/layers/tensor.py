"""Tensor creation/manipulation layers (reference
python/paddle/fluid/layers/tensor.py)."""

import numpy as np

from .. import framework
from ..framework import Variable, convert_np_dtype
from ..layer_helper import LayerHelper
from ..initializer import Constant

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "scale",
    "increment",
    "argmax",
    "argmin",
    "argsort",
    "reverse",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype, persistable=persistable)


def create_parameter(
    shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None
):
    helper = LayerHelper("create_parameter", name=name)
    from ..param_attr import ParamAttr

    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=helper.name
    )
    helper.set_variable_initializer(var, Constant(value=float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_np_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"in_dtype": x.dtype, "out_dtype": dtype},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=helper.input_dtype())
    helper.append_op(
        type="concat",
        inputs={"X": [v.name for v in input]},
        outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=helper.input_dtype())
    helper.append_op(
        type="sum",
        inputs={"X": [v.name for v in input]},
        outputs={"Out": [out.name]},
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(
            type="assign", inputs={"X": [input.name]}, outputs={"Out": [output.name]}
        )
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=convert_np_dtype(input.dtype)
            )
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output.name]},
            attrs={
                "shape": list(input.shape),
                "dtype": output.dtype,
                "values": input.reshape(-1).tolist(),
            },
        )
    else:
        raise TypeError("assign expects Variable or ndarray")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=convert_np_dtype(dtype))
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out.name]},
        attrs={
            "shape": [int(s) for s in shape],
            "dtype": convert_np_dtype(dtype),
            "value": float(value),
        },
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=convert_np_dtype(dtype))
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "shape": [int(s) for s in shape],
            "dtype": convert_np_dtype(dtype),
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    return helper.append_activation(out)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="increment",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"step": float(value)},
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="arg_max",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="arg_min",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ids = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="argsort",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name], "Indices": [ids.name]},
        attrs={"axis": axis},
    )
    return out, ids


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op(
        type="reverse",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return out
