"""Sequence layers over the padded+lengths representation (reference
python/paddle/fluid/layers/nn.py: dynamic_lstm, dynamic_gru, sequence_pool,
sequence_softmax, sequence_conv, sequence_first/last_step, gru_unit).

A ragged variable carries `_len_name` pointing at its `<name>@LEN` companion
(created by layers.data(lod_level=1) / propagated by sequence-aware layers)."""

from ..framework import Variable
from ..initializer import Constant
from ..layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm",
    "dynamic_gru",
    "gru_unit",
    "sequence_pool",
    "sequence_softmax",
    "sequence_conv",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_reverse",
    "sequence_expand",
    "sequence_expand_as",
    "sequence_pad",
    "sequence_unpad",
    "sequence_mask",
    "sequence_concat",
    "sequence_slice",
    "sequence_erase",
    "sequence_reshape",
    "sequence_scatter",
    "sequence_enumerate",
    "im2sequence",
    "row_conv",
]


def seq_len_of(var):
    name = getattr(var, "_len_name", None)
    if name is None:
        raise ValueError(
            "variable %r has no sequence-length companion; build ragged inputs "
            "with layers.data(..., lod_level=1) or propagate through sequence "
            "layers" % var.name
        )
    return name


def _propagate(dst, src):
    if getattr(src, "_len_name", None):
        dst._len_name = src._len_name
    return dst


def dynamic_lstm(
    input,
    size,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
):
    """reference layers/nn.py dynamic_lstm → lstm op. `input` is the fc
    projection (b, t, 4*hidden); returns (hidden, cell) sequences. h_0/c_0
    are optional (batch, hidden) warm-start states (reference nn.py:362: both
    must be given together)."""
    if (h_0 is None) != (c_0 is None):
        raise ValueError(
            "dynamic_lstm needs h_0 and c_0 together (reference layers/nn.py "
            "dynamic_lstm contract)"
        )
    helper = LayerHelper("lstm", **locals())
    hidden_size = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_size, 4 * hidden_size], dtype=dtype
    )
    bias_size = [1, 7 * hidden_size] if use_peepholes else [1, 4 * hidden_size]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {
        "Input": [input.name],
        "Weight": [weight.name],
        "Bias": [bias.name],
        "SeqLen": [seq_len_of(input)],
    }
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
        inputs["C0"] = [c_0.name]
    helper.append_op(
        type="dynamic_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden.name], "Cell": [cell.name]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    _propagate(hidden, input)
    _propagate(cell, input)
    return hidden, cell


def dynamic_gru(
    input,
    size,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    gate_activation="sigmoid",
    candidate_activation="tanh",
    h_0=None,
    name=None,
):
    helper = LayerHelper("gru", **locals())
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {
        "Input": [input.name],
        "Weight": [weight.name],
        "Bias": [bias.name],
        "SeqLen": [seq_len_of(input)],
    }
    if h_0 is not None:
        # (batch, hidden) warm-start state (reference layers/nn.py:453)
        inputs["H0"] = [h_0.name]
    helper.append_op(
        type="dynamic_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden.name]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return _propagate(hidden, input)


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None, activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit", **locals())
    dtype = input.dtype
    hidden_size = size // 3
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_size, 3 * hidden_size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * hidden_size], dtype=dtype, is_bias=True
    )
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gru_unit",
        inputs={
            "Input": [input.name],
            "HiddenPrev": [hidden.name],
            "Weight": [weight.name],
            "Bias": [bias.name],
        },
        outputs={
            "Gate": [gate.name],
            "ResetHiddenPrev": [reset_hidden.name],
            "Hidden": [updated.name],
        },
        attrs={"activation": activation, "gate_activation": gate_activation},
    )
    return updated, reset_hidden, gate


def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_softmax",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name]},
    )
    return _propagate(out, input)


def sequence_conv(
    input,
    num_filters,
    filter_size=3,
    filter_stride=1,
    padding=None,
    bias_attr=None,
    param_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("sequence_conv", **locals())
    dtype = input.dtype
    d_in = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[filter_size * d_in, num_filters], dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={
            "X": [input.name],
            "Filter": [w.name],
            "SeqLen": [seq_len_of(input)],
        },
        outputs={"Out": [out.name]},
        attrs={
            "contextLength": filter_size,
            "contextStart": -((filter_size - 1) // 2),
            "contextStride": filter_stride,
        },
    )
    _propagate(out, input)
    pre_act = helper.append_bias_op(out, dim_start=2)
    _propagate(pre_act, input)
    result = helper.append_activation(pre_act)
    return _propagate(result, input)


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_reverse",
        inputs={"X": [x.name], "SeqLen": [seq_len_of(x)]},
        outputs={"Y": [out.name]},
    )
    return _propagate(out, x)


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"ref_level": ref_level},
    )
    return _propagate(out, y)


def _new_len_var(helper, out):
    """Create the `<out>@LEN` companion var (before the op that writes it is
    appended, so shape inference can resolve it) and attach it."""
    len_name = out.name + "@LEN"
    helper.main_program.current_block().create_var(
        name=len_name, shape=(-1,), dtype="int32"
    )
    out._len_name = len_name
    return len_name


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """reference layers/nn.py sequence_pad → sequence_pad_op.cc. Returns
    (padded, lengths); the padded-dense rep makes this mostly a pad-value
    fill plus optional capacity change."""
    helper = LayerHelper("sequence_pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    # the op's Length output (clamped to the capacity) becomes the companion,
    # NOT the input lengths — they diverge when maxlen truncates
    len_name = _new_len_var(helper, out)
    helper.append_op(
        type="sequence_pad",
        inputs={
            "X": [x.name],
            "PadValue": [pad_value.name],
            "SeqLen": [seq_len_of(x)],
        },
        outputs={"Out": [out.name], "Length": [len_name]},
        attrs={"padded_length": -1 if maxlen is None else int(maxlen)},
    )
    return out, helper.main_program.current_block().var(len_name)


def sequence_unpad(x, length, name=None):
    """reference layers/nn.py sequence_unpad → sequence_unpad_op.cc; output
    carries `length` as its ragged companion."""
    helper = LayerHelper("sequence_unpad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_unpad",
        inputs={"X": [x.name], "Length": [length.name]},
        outputs={"Out": [out.name]},
    )
    out._len_name = length.name
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference layers/nn.py sequence_mask → sequence_mask_op.cc. maxlen is
    required (static shapes under XLA)."""
    if maxlen is None:
        raise ValueError("sequence_mask requires maxlen under the XLA lowering")
    helper = LayerHelper("sequence_mask", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_mask",
        inputs={"X": [x.name]},
        outputs={"Y": [out.name]},
        attrs={"maxlen": int(maxlen), "out_dtype": dtype},
    )
    out.stop_gradient = True
    return out


def sequence_concat(input, name=None):
    """reference layers/nn.py sequence_concat → sequence_concat_op.cc:
    per-row concatenation along time."""
    helper = LayerHelper("sequence_concat", **locals())
    out = helper.create_variable_for_type_inference(input[0].dtype)
    len_name = _new_len_var(helper, out)
    helper.append_op(
        type="sequence_concat",
        inputs={
            "X": [v.name for v in input],
            "SeqLen": [seq_len_of(v) for v in input],
        },
        outputs={"Out": [out.name], "OutLen": [len_name]},
    )
    return out


def sequence_expand_as(x, y, name=None):
    """reference layers/nn.py sequence_expand_as → sequence_expand_as_op.cc."""
    helper = LayerHelper("sequence_expand_as", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand_as",
        inputs={"X": [x.name], "Y": [y.name], "SeqLen": [seq_len_of(y)]},
        outputs={"Out": [out.name]},
    )
    out._len_name = seq_len_of(y)
    return out


def sequence_slice(input, offset, length, name=None):
    """reference layers/nn.py sequence_slice → sequence_slice_op.h."""
    helper = LayerHelper("sequence_slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    len_name = _new_len_var(helper, out)
    helper.append_op(
        type="sequence_slice",
        inputs={
            "X": [input.name],
            "Offset": [offset.name],
            "Length": [length.name],
        },
        outputs={"Out": [out.name], "OutLen": [len_name]},
    )
    return out


def sequence_erase(input, tokens, name=None):
    """reference sequence_erase_op.cc: drop listed tokens, re-compact."""
    helper = LayerHelper("sequence_erase", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    len_name = _new_len_var(helper, out)
    helper.append_op(
        type="sequence_erase",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name], "OutLen": [len_name]},
        attrs={"tokens": list(tokens)},
    )
    return out


def sequence_reshape(input, new_dim):
    """reference sequence_reshape_op.cc."""
    helper = LayerHelper("sequence_reshape", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    len_name = _new_len_var(helper, out)
    helper.append_op(
        type="sequence_reshape",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name], "OutLen": [len_name]},
        attrs={"new_dim": int(new_dim)},
    )
    return out


def sequence_scatter(input, index, updates, name=None):
    """reference sequence_scatter_op.cc."""
    helper = LayerHelper("sequence_scatter", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_scatter",
        inputs={
            "X": [input.name],
            "Ids": [index.name],
            "Updates": [updates.name],
            "SeqLen": [seq_len_of(index)],
        },
        outputs={"Out": [out.name]},
    )
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """reference sequence_enumerate_op.cc: sliding id windows."""
    helper = LayerHelper("sequence_enumerate", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_enumerate",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name]},
        attrs={"win_size": int(win_size), "pad_value": int(pad_value)},
    )
    out._len_name = seq_len_of(input)
    return out


def im2sequence(
    input,
    filter_size=1,
    stride=1,
    padding=0,
    input_image_size=None,
    out_stride=1,
    name=None,
):
    """Image → patch sequence (reference layers/nn.py im2sequence →
    im2sequence_op.cc). Without input_image_size, output rows all share
    length out_h*out_w (emitted as a fill_constant_batch_size_like
    companion). With input_image_size — a (batch, 2) tensor of per-image
    (real_h, real_w) — each row's valid length follows the reference's
    real-size formula (im2sequence_op.h:52-110) via ceil(real/out_stride),
    and the op emits the ragged lengths itself."""
    from .nn import _pair
    from .tensor import fill_constant_batch_size_like

    if input_image_size is None and out_stride != 1:
        raise ValueError(
            "im2sequence out_stride is only meaningful with input_image_size "
            "(reference im2sequence_op.h real-size mode)"
        )
    helper = LayerHelper("im2sequence", **locals())
    kernels = _pair(filter_size)
    strides = _pair(stride)
    pads = padding if isinstance(padding, (list, tuple)) and len(padding) == 4 else _pair(padding) * 2
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input.name]}
    outputs = {"Out": [out.name]}
    attrs = {"kernels": kernels, "strides": strides, "paddings": list(pads)}
    if input_image_size is not None:
        inputs["Y"] = [input_image_size.name]
        attrs["out_stride"] = _pair(out_stride)
        outputs["OutLen"] = [_new_len_var(helper, out)]
    helper.append_op(
        type="im2sequence", inputs=inputs, outputs=outputs, attrs=attrs
    )
    if input_image_size is not None:
        return out
    h, w = input.shape[2], input.shape[3]
    oh = (h + pads[0] + pads[2] - kernels[0]) // strides[0] + 1
    ow = (w + pads[1] + pads[3] - kernels[1]) // strides[1] + 1
    lens = fill_constant_batch_size_like(
        input, shape=[-1], dtype="int32", value=oh * ow
    )
    out._len_name = lens.name
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead convolution (reference layers/nn.py row_conv →
    row_conv_op.cc)."""
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    d = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[future_context_size + 1, d], dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="row_conv",
        inputs={
            "X": [input.name],
            "Filter": [w.name],
            "SeqLen": [seq_len_of(input)],
        },
        outputs={"Out": [out.name]},
    )
    out._len_name = seq_len_of(input)
    return helper.append_activation(out)
