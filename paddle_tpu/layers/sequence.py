"""Sequence layers over the padded+lengths representation (reference
python/paddle/fluid/layers/nn.py: dynamic_lstm, dynamic_gru, sequence_pool,
sequence_softmax, sequence_conv, sequence_first/last_step, gru_unit).

A ragged variable carries `_len_name` pointing at its `<name>@LEN` companion
(created by layers.data(lod_level=1) / propagated by sequence-aware layers)."""

from ..framework import Variable
from ..initializer import Constant
from ..layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm",
    "dynamic_gru",
    "gru_unit",
    "sequence_pool",
    "sequence_softmax",
    "sequence_conv",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_reverse",
    "sequence_expand",
]


def seq_len_of(var):
    name = getattr(var, "_len_name", None)
    if name is None:
        raise ValueError(
            "variable %r has no sequence-length companion; build ragged inputs "
            "with layers.data(..., lod_level=1) or propagate through sequence "
            "layers" % var.name
        )
    return name


def _propagate(dst, src):
    if getattr(src, "_len_name", None):
        dst._len_name = src._len_name
    return dst


def dynamic_lstm(
    input,
    size,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=True,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
):
    """reference layers/nn.py dynamic_lstm → lstm op. `input` is the fc
    projection (b, t, 4*hidden); returns (hidden, cell) sequences."""
    if h_0 is not None or c_0 is not None:
        raise NotImplementedError(
            "dynamic_lstm h_0/c_0 initial state lands with the seq2seq tier; "
            "zeros are used today"
        )
    helper = LayerHelper("lstm", **locals())
    hidden_size = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_size, 4 * hidden_size], dtype=dtype
    )
    bias_size = [1, 7 * hidden_size] if use_peepholes else [1, 4 * hidden_size]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="dynamic_lstm",
        inputs={
            "Input": [input.name],
            "Weight": [weight.name],
            "Bias": [bias.name],
            "SeqLen": [seq_len_of(input)],
        },
        outputs={"Hidden": [hidden.name], "Cell": [cell.name]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    _propagate(hidden, input)
    _propagate(cell, input)
    return hidden, cell


def dynamic_gru(
    input,
    size,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    gate_activation="sigmoid",
    candidate_activation="tanh",
    h_0=None,
    name=None,
):
    if h_0 is not None:
        raise NotImplementedError(
            "dynamic_gru h_0 initial state lands with the seq2seq tier; "
            "zeros are used today"
        )
    helper = LayerHelper("gru", **locals())
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="dynamic_gru",
        inputs={
            "Input": [input.name],
            "Weight": [weight.name],
            "Bias": [bias.name],
            "SeqLen": [seq_len_of(input)],
        },
        outputs={"Hidden": [hidden.name]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    return _propagate(hidden, input)


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None, activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit", **locals())
    dtype = input.dtype
    hidden_size = size // 3
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_size, 3 * hidden_size], dtype=dtype
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * hidden_size], dtype=dtype, is_bias=True
    )
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gru_unit",
        inputs={
            "Input": [input.name],
            "HiddenPrev": [hidden.name],
            "Weight": [weight.name],
            "Bias": [bias.name],
        },
        outputs={
            "Gate": [gate.name],
            "ResetHiddenPrev": [reset_hidden.name],
            "Hidden": [updated.name],
        },
        attrs={"activation": activation, "gate_activation": gate_activation},
    )
    return updated, reset_hidden, gate


def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_softmax",
        inputs={"X": [input.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Out": [out.name]},
    )
    return _propagate(out, input)


def sequence_conv(
    input,
    num_filters,
    filter_size=3,
    filter_stride=1,
    padding=None,
    bias_attr=None,
    param_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("sequence_conv", **locals())
    dtype = input.dtype
    d_in = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[filter_size * d_in, num_filters], dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={
            "X": [input.name],
            "Filter": [w.name],
            "SeqLen": [seq_len_of(input)],
        },
        outputs={"Out": [out.name]},
        attrs={
            "contextLength": filter_size,
            "contextStart": -((filter_size - 1) // 2),
            "contextStride": filter_stride,
        },
    )
    _propagate(out, input)
    pre_act = helper.append_bias_op(out, dim_start=2)
    _propagate(pre_act, input)
    result = helper.append_activation(pre_act)
    return _propagate(result, input)


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_reverse",
        inputs={"X": [x.name], "SeqLen": [seq_len_of(x)]},
        outputs={"Y": [out.name]},
    )
    return _propagate(out, x)


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"ref_level": ref_level},
    )
    return _propagate(out, y)
