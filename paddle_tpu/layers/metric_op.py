"""In-graph metric layers (reference python/paddle/fluid/layers/metric_op.py:
accuracy, auc)."""

from ..layer_helper import LayerHelper
from ..initializer import Constant

__all__ = ["accuracy", "auc", "positive_negative_pair"]


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference metric_op.py accuracy → top_k + accuracy ops)."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    topk_indices = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="top_k",
        inputs={"X": [input.name]},
        outputs={"Out": [topk_out.name], "Indices": [topk_indices.name]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference(dtype="float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference(dtype="int32")
    if total is None:
        total = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="accuracy",
        inputs={
            "Out": [topk_out.name],
            "Indices": [topk_indices.name],
            "Label": [label.name],
        },
        outputs={
            "Accuracy": [acc_out.name],
            "Correct": [correct.name],
            "Total": [total.name],
        },
    )
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """Streaming AUC (reference metric_op.py auc → auc op with persistable
    stat buffers updated in-graph)."""
    helper = LayerHelper("auc")
    auc_out = helper.create_variable_for_type_inference(dtype="float32")
    batch_out = helper.create_variable_for_type_inference(dtype="float32")
    stat_pos = helper.create_or_get_global_variable(
        name=helper.name + "_stat_pos", dtype="float32", shape=[num_thresholds + 1]
    )
    stat_neg = helper.create_or_get_global_variable(
        name=helper.name + "_stat_neg", dtype="float32", shape=[num_thresholds + 1]
    )
    for var in [stat_pos, stat_neg]:
        helper.set_variable_initializer(var, Constant(value=0.0))
    helper.append_op(
        type="auc",
        inputs={
            "Predict": [input.name],
            "Label": [label.name],
            "StatPos": [stat_pos.name],
            "StatNeg": [stat_neg.name],
        },
        outputs={
            "AUC": [auc_out.name],
            "StatPosOut": [stat_pos.name],
            "StatNegOut": [stat_neg.name],
        },
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    auc_out.stop_gradient = True
    return auc_out, [batch_out, stat_pos, stat_neg]


def positive_negative_pair(score, label, query_id, weight=None, column=-1):
    """Pairwise ranking metric over within-query item pairs (reference
    metric_op.py-era positive_negative_pair op; the LETOR/mq2007 evaluation
    shipped in dataset/mq2007.py). Returns (positive, negative, neutral)
    pair counts as float32 [1] tensors; higher positive/negative ratio means
    the scorer orders items more like the relevance labels."""
    helper = LayerHelper("positive_negative_pair")
    pos = helper.create_variable_for_type_inference(dtype="float32")
    neg = helper.create_variable_for_type_inference(dtype="float32")
    neu = helper.create_variable_for_type_inference(dtype="float32")
    inputs = {
        "Score": [score.name],
        "Label": [label.name],
        "QueryID": [query_id.name],
    }
    if weight is not None:
        inputs["Weight"] = [weight.name]
    helper.append_op(
        type="positive_negative_pair",
        inputs=inputs,
        outputs={
            "PositivePair": [pos.name],
            "NegativePair": [neg.name],
            "NeutralPair": [neu.name],
        },
        attrs={"column": column},
    )
    for v in (pos, neg, neu):
        v.stop_gradient = True
    return pos, neg, neu
