"""fluid-compatible layers namespace (reference python/paddle/fluid/layers/).

All public layer functions are re-exported flat, so user code written as
`fluid.layers.fc(...)` works unchanged against `paddle_tpu.layers`.
"""

from . import control_flow, detection, io, loss, metric_op, nn, ops, sequence, tensor
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import learning_rate_scheduler

__all__ = (
    control_flow.__all__
    + detection.__all__
    + io.__all__
    + loss.__all__
    + metric_op.__all__
    + nn.__all__
    + ops.__all__
    + sequence.__all__
    + tensor.__all__
    + learning_rate_scheduler.__all__
)
