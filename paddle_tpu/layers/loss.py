"""Structured-prediction / ranking / sampled-loss layers (reference
python/paddle/fluid/layers/nn.py: linear_chain_crf, crf_decoding, warpctc,
ctc_greedy_decoder, nce, hsigmoid, cos_sim, bpr_loss, margin_rank_loss,
rank_loss, edit_distance, sampling_id, huber_loss).

Sequence arguments follow the padded-dense + `<name>@LEN` companion
convention (layers/sequence.py); the reference used LoD tensors."""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .sequence import _propagate, seq_len_of

__all__ = [
    "linear_chain_crf",
    "crf_decoding",
    "warpctc",
    "ctc_greedy_decoder",
    "nce",
    "hsigmoid",
    "cos_sim",
    "bpr_loss",
    "margin_rank_loss",
    "rank_loss",
    "modified_huber_loss",
    "edit_distance",
    "sampling_id",
    "huber_loss",
]


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF negative log-likelihood (reference layers/nn.py linear_chain_crf →
    linear_chain_crf_op.cc). `input` is the [B, T, D] emission; the
    [D+2, D] transition parameter is created here (rows 0/1: start/end)."""
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr,
        shape=[size + 2, size],
        dtype=helper.input_dtype(),
    )
    seqlen = length.name if length is not None else seq_len_of(input)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    emission_exps = helper.create_variable_for_type_inference(input.dtype)
    transition_exps = helper.create_variable_for_type_inference(input.dtype)
    log_likelihood = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="linear_chain_crf",
        inputs={
            "Emission": [input.name],
            "Transition": [transition.name],
            "Label": [label.name],
            "SeqLen": [seqlen],
        },
        outputs={
            "Alpha": [alpha.name],
            "EmissionExps": [emission_exps.name],
            "TransitionExps": [transition_exps.name],
            "LogLikelihood": [log_likelihood.name],
        },
    )
    return log_likelihood


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the trained transition parameter (reference
    layers/nn.py crf_decoding → crf_decoding_op.cc)."""
    helper = LayerHelper("crf_decoding", **locals())
    name = param_attr.name if isinstance(param_attr, ParamAttr) else str(param_attr)
    transition = helper.main_program.global_block()._var_recursive(name)
    seqlen = length.name if length is not None else seq_len_of(input)
    viterbi_path = helper.create_variable_for_type_inference("int64")
    inputs = {
        "Emission": [input.name],
        "Transition": [transition.name],
        "SeqLen": [seqlen],
    }
    if label is not None:
        inputs["Label"] = [label.name]
    helper.append_op(
        type="crf_decoding",
        inputs=inputs,
        outputs={"ViterbiPath": [viterbi_path.name]},
    )
    viterbi_path.stop_gradient = True
    return _propagate(viterbi_path, input)


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss (reference layers/nn.py warpctc → warpctc_op.cc). `input`
    is [B, T, num_classes+1] raw logits, `label` [B, L, 1] int; both carry
    length companions."""
    helper = LayerHelper("warpctc", **locals())
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="warpctc",
        inputs={
            "Logits": [input.name],
            "Label": [label.name],
            "LogitsLength": [seq_len_of(input)],
            "LabelLength": [seq_len_of(label)],
        },
        outputs={"Loss": [loss.name]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    """argmax per step, then collapse repeats and drop blanks (reference
    layers/nn.py ctc_greedy_decoder = topk + ctc_align_op)."""
    from .nn import topk

    from .sequence import _new_len_var

    helper = LayerHelper("ctc_greedy_decoder", **locals())
    _, ids = topk(input, k=1)
    out = helper.create_variable_for_type_inference("int64")
    out_len_name = _new_len_var(helper, out)
    helper.append_op(
        type="ctc_align",
        inputs={"Input": [ids.name], "SeqLen": [seq_len_of(input)]},
        outputs={"Output": [out.name], "OutLen": [out_len_name]},
        attrs={"blank": blank, "padding_value": 0},
    )
    out.stop_gradient = True
    return out


def nce(
    input,
    label,
    num_total_classes,
    sample_weight=None,
    param_attr=None,
    bias_attr=None,
    num_neg_samples=None,
    name=None,
    sampler="uniform",
    custom_dist=None,
    seed=0,
    is_sparse=False,
):
    """Noise-contrastive estimation (reference layers/nn.py nce → nce_op.cc).

    custom_dist: list/array of num_total_classes sampling probabilities
    (reference sampler=2 CustomSampler); sample_weight: (batch, 1) Variable
    scaling each row's cost (reference nce_op.h:159)."""
    helper = LayerHelper("nce", **locals())
    dim = input.shape[-1]
    num_neg_samples = int(num_neg_samples or 10)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_total_classes, dim],
        dtype=input.dtype,
    )
    inputs = {"Input": [input.name], "Label": [label.name], "Weight": [w.name]}
    # reference nn.py nce contract: custom_dist and sampler="custom_dist"
    # come together; custom_dist does not silently override another sampler
    if (custom_dist is not None) and sampler not in ("uniform", "custom_dist"):
        raise ValueError(
            "custom_dist conflicts with sampler=%r; pass "
            "sampler='custom_dist' (or leave the default)" % sampler
        )
    if sampler == "custom_dist" and custom_dist is None:
        raise ValueError("sampler='custom_dist' requires custom_dist")
    if custom_dist is not None:
        from .tensor import assign

        dist = np.asarray(custom_dist, dtype="float32").reshape(-1)
        if dist.shape[0] != num_total_classes:
            raise ValueError(
                "custom_dist must have num_total_classes=%d entries, got %d"
                % (num_total_classes, dist.shape[0])
            )
        probs = assign(dist)
        inputs["CustomDistProbs"] = [probs.name]
        sampler = "custom_dist"
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight.name]
    if not (bias_attr is False):
        b = helper.create_parameter(
            attr=helper.bias_attr,
            shape=[num_total_classes, 1],
            dtype=input.dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b.name]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(input.dtype)
    sample_labels = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="nce",
        inputs=inputs,
        outputs={
            "Cost": [cost.name],
            "SampleLogits": [sample_logits.name],
            "SampleLabels": [sample_labels.name],
        },
        attrs={
            "num_total_classes": num_total_classes,
            "num_neg_samples": num_neg_samples,
            "sampler": sampler,
            "seed": seed,
        },
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None, name=None):
    """Hierarchical sigmoid over the implicit complete binary tree (reference
    layers/nn.py hsigmoid → hierarchical_sigmoid_op.cc)."""
    helper = LayerHelper("hsigmoid", **locals())
    dim = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes - 1, dim], dtype=input.dtype
    )
    inputs = {"X": [input.name], "Label": [label.name], "W": [w.name]}
    if not (bias_attr is False):
        b = helper.create_parameter(
            attr=helper.bias_attr,
            shape=[num_classes - 1, 1],
            dtype=input.dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b.name]
    cost = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs=inputs,
        outputs={"Cost": [cost.name], "PreOut": [pre_out.name]},
        attrs={"num_classes": num_classes},
    )
    return cost


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim", **locals())
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(
        type="cos_sim",
        inputs={"X": [X.name], "Y": [Y.name]},
        outputs={"Out": [out.name], "XNorm": [xnorm.name], "YNorm": [ynorm.name]},
    )
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="bpr_loss",
        inputs={"X": [input.name], "Label": [label.name]},
        outputs={"Cost": [out.name]},
    )
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="margin_rank_loss",
        inputs={"Label": [label.name], "X1": [left.name], "X2": [right.name]},
        outputs={"Out": [out.name], "Activated": [act.name]},
        attrs={"margin": margin},
    )
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="rank_loss",
        inputs={"Label": [label.name], "Left": [left.name], "Right": [right.name]},
        outputs={"Out": [out.name]},
    )
    return out


def modified_huber_loss(x, y, name=None):
    helper = LayerHelper("modified_huber_loss", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    inter = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="modified_huber_loss",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name], "IntermediateVal": [inter.name]},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input.name], "Y": [label.name]},
        outputs={"Out": [out.name], "Residual": [residual.name]},
        attrs={"delta": delta},
    )
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    """Batched Levenshtein distance (reference layers/nn.py edit_distance →
    edit_distance_op.cc). `ignored_tokens` are erased from both sequences
    before the distance, via sequence_erase ops as the reference does
    (reference layers/nn.py:4402-4417). Returns (distance [B,1], seq_num [1])."""
    if ignored_tokens:
        from .sequence import sequence_erase

        input = sequence_erase(input, list(ignored_tokens))
        label = sequence_erase(label, list(ignored_tokens))
    helper = LayerHelper("edit_distance", **locals())
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance",
        inputs={
            "Hyps": [input.name],
            "Refs": [label.name],
            "HypsLen": [seq_len_of(input)],
            "RefsLen": [seq_len_of(label)],
        },
        outputs={"Out": [out.name], "SequenceNum": [seq_num.name]},
        attrs={"normalized": normalized},
    )
    out.stop_gradient = True
    seq_num.stop_gradient = True
    return out, seq_num


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    """Sample a column per row from a probability matrix (reference
    layers/nn.py sampling_id → sampling_id_op.cc)."""
    helper = LayerHelper("sampling_id", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="sampling_id",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"seed": seed},
    )
    out.stop_gradient = True
    return out
