"""Learning-rate schedules as graph ops (reference
python/paddle/fluid/layers/learning_rate_scheduler.py — the schedule is part
of the program, driven by the auto-incremented global step counter, so it
compiles into the same XLA module as the training step)."""

import math

from .. import framework
from . import nn, ops, tensor

__all__ = [
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
]


def _decay_step_counter(begin=0):
    counter = nn.autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1
    )
    return tensor.cast(counter, "float32")


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5) (reference
    learning_rate_scheduler.py:noam_decay; used by Transformer)."""
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter(begin=1)
        a = step ** -0.5
        b = (warmup_steps ** -1.5) * step
        lr = (d_model ** -0.5) * nn.elementwise_min(a, b)
        return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    # lr * decay_rate^div  ==  exp(log(lr) + div*log(decay_rate))
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = ops.floor(div)
        val = tensor.scale(div, scale=math.log(decay_rate), bias=math.log(learning_rate))
        return ops.exp(val)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = ops.floor(div)
        val = tensor.scale(div, scale=-decay_rate, bias=math.log(learning_rate))
        return ops.exp(val)


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = ops.floor(div)
        denom = tensor.scale(div, scale=float(decay_rate), bias=1.0)
        return nn.elementwise_div(
            tensor.fill_constant([1], "float32", float(learning_rate)), denom
        )


def polynomial_decay(
    learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False
):
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter()
        if cycle:
            ratio = step / float(decay_steps)
            ceiled = nn.elementwise_max(
                ops.ceil(ratio), tensor.fill_constant([1], "float32", 1.0)
            )
            decay_steps_var = tensor.scale(ceiled, scale=float(decay_steps))
            frac = nn.elementwise_div(step, decay_steps_var)
        else:
            capped = nn.elementwise_min(
                step, tensor.fill_constant([1], "float32", float(decay_steps))
            )
            frac = tensor.scale(capped, scale=1.0 / decay_steps)
        base = tensor.scale(frac, scale=-1.0, bias=1.0) ** float(power)
        return tensor.scale(
            base, scale=float(learning_rate - end_learning_rate), bias=float(end_learning_rate)
        )


def piecewise_decay(boundaries, values):
    """Piecewise-constant schedule. The reference builds a Switch control-flow
    block (learning_rate_scheduler.py:piecewise_decay); here it lowers to a
    branch-free sum of interval indicators — XLA-friendly (no control flow)."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter()
        pieces = []
        prev = None
        for i, v in enumerate(values):
            lo = boundaries[i - 1] if i > 0 else None
            hi = boundaries[i] if i < len(boundaries) else None
            ind = None
            if lo is not None:
                ge = tensor.cast(step >= float(lo), "float32")
                ind = ge
            if hi is not None:
                lt = tensor.cast(step < float(hi), "float32")
                ind = lt if ind is None else nn.elementwise_mul(ind, lt)
            piece = (
                tensor.fill_constant([1], "float32", float(v))
                if ind is None
                else tensor.scale(ind, scale=float(v))
            )
            pieces.append(piece)
        lr = pieces[0]
        for p in pieces[1:]:
            lr = nn.elementwise_add(lr, p)
        return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    with framework.default_main_program()._lr_schedule_guard():
        step = _decay_step_counter()
        epoch = ops.floor(tensor.scale(step, scale=1.0 / step_each_epoch))
        inner = tensor.scale(epoch, scale=math.pi / epochs)
        cos_v = ops.cos(inner)
        return tensor.scale(cos_v, scale=0.5 * learning_rate, bias=0.5 * learning_rate)
