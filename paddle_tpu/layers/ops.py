"""Auto-generated unary layer wrappers (reference layers/ops.py, which
generates these from the C++ op protos via layer_function_generator.py; here
generated from the op registry)."""

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid",
    "logsigmoid",
    "exp",
    "tanh",
    "tanh_shrink",
    "softshrink",
    "sqrt",
    "rsqrt",
    "abs",
    "ceil",
    "floor",
    "cos",
    "sin",
    "round",
    "reciprocal",
    "square",
    "softplus",
    "softsign",
    "brelu",
    "soft_relu",
    "elu",
    "relu6",
    "pow",
    "stanh",
    "hard_sigmoid",
    "swish",
    "gelu",
    "thresholded_relu",
    "hard_shrink",
    "cumsum",
    "sign",
    "log_softmax",
]

__all__ = list(_UNARY_OPS) + ["uniform_random", "gaussian_random"]


def _make_unary(op_type):
    def layer(x, *args, **kwargs):
        # positional/keyword attrs pass straight through to the op
        attrs = dict(kwargs)
        attrs.pop("name", None)
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x.name]},
            outputs={"Out": [out.name]},
            attrs=attrs,
        )
        return out

    layer.__name__ = op_type
    layer.__doc__ = "unary op %s (see ops/core_ops.py)" % op_type
    return layer


for _name in _UNARY_OPS:
    globals()[_name] = _make_unary(_name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random",
        outputs={"Out": [out.name]},
        attrs={"shape": list(shape), "dtype": dtype, "min": min, "max": max, "seed": seed},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random",
        outputs={"Out": [out.name]},
        attrs={"shape": list(shape), "dtype": dtype, "mean": mean, "std": std, "seed": seed},
    )
    return out
