"""Control-flow layers: While / Switch / IfElse / ConditionalBlock /
StaticRNN / DynamicRNN, compare wrappers, tensor arrays, Print.

Reference analog: python/paddle/fluid/layers/control_flow.py (While :655,
StaticRNN :429, DynamicRNN :1546, ConditionalBlock :1207, Switch :1290,
lod_rank_table :742, array ops). Sub-blocks are built exactly like the
reference (program._create_block / _rollback) and the completed op carries the
Block as an attr; the TPU-first difference is how they execute — the ops lower
the sub-block into the enclosing XLA computation (lax.while_loop / lax.cond /
lax.scan, see ops/control_flow_ops.py) instead of a nested C++ Executor.

IfElse is redesigned for TPU: the reference splits the batch by the condition
mask and runs each branch on its subset (dynamic shapes); here both branches
compute on the full batch and merge with a masked select — the standard SPMD
treatment of data-dependent branching (no dynamic shapes, XLA-friendly).
"""

import contextlib

from .. import unique_name
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from ..ops.registry import EMPTY_VAR_NAME as _EMPTY

__all__ = [
    "While",
    "Switch",
    "IfElse",
    "ConditionalBlock",
    "StaticRNN",
    "DynamicRNN",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "logical_and",
    "logical_or",
    "logical_xor",
    "logical_not",
    "array_read",
    "array_write",
    "array_length",
    "create_array",
    "lod_tensor_to_array",
    "array_to_lod_tensor",
    "lod_rank_table",
    "max_sequence_len",
    "reorder_lod_tensor_by_rank",
    "shrink_memory",
    "Print",
]


# ---------------------------------------------------------------------------
# compare / logical wrappers (reference keeps these in layers/control_flow.py
# and layers/ops.py; lowerings in ops/core_ops.py)
# ---------------------------------------------------------------------------


def _binary_bool(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        type=op_type,
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [cond.name]},
    )
    cond.dtype = "bool"
    cond.stop_gradient = True
    return cond


def less_than(x, y, cond=None, force_cpu=None):
    return _binary_bool("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _binary_bool("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _binary_bool("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _binary_bool("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _binary_bool("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _binary_bool("not_equal", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _binary_bool("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _binary_bool("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _binary_bool("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not")
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        type="logical_not", inputs={"X": [x.name]}, outputs={"Out": [out.name]}
    )
    out.dtype = "bool"
    out.stop_gradient = True
    return out


# ---------------------------------------------------------------------------
# sub-block analysis shared by While / ConditionalBlock
# ---------------------------------------------------------------------------


def _external_reads_writes(sub):
    """First-occurrence-ordered lists of names the sub-block reads/writes that
    live in an ancestor block (the reference's while_op input/output discovery
    in layers/control_flow.py While.complete)."""
    parent = sub.parent_block
    reads, writes = [], []
    seen_r, seen_w = set(), set()
    for op in sub.ops:
        for n in op.input_arg_names:
            if n != _EMPTY and n not in seen_r:
                seen_r.add(n)
                reads.append(n)
        for n in op.output_arg_names:
            if n != _EMPTY and n not in seen_w:
                seen_w.add(n)
                writes.append(n)
    ext_r = [
        n for n in reads if n not in sub.vars and parent.has_var_recursive(n)
    ]
    ext_w = [
        n for n in writes if n not in sub.vars and parent.has_var_recursive(n)
    ]
    return ext_r, ext_w


class While:
    """fluid.layers.While (reference layers/control_flow.py:655).

    cond must be a scalar bool Variable, updated inside the block (e.g. by
    ``less_than(i, n, cond=cond)``). With ``maximum_iterations`` set the loop
    lowers to a masked lax.scan and is reverse-differentiable; without it, to
    an open-ended XLA While (forward only).
    """

    def __init__(self, cond, is_test=False, name=None, maximum_iterations=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test
        self.maximum_iterations = maximum_iterations
        self._main = default_main_program()
        self._sub = None

    @contextlib.contextmanager
    def block(self):
        self._sub = self._main._create_block()
        try:
            yield
        finally:
            self._main._rollback()
        self._complete()

    def _complete(self):
        sub = self._sub
        parent = sub.parent_block
        ext_r, carried = _external_reads_writes(sub)
        if self.cond_var.name not in carried:
            raise ValueError(
                "While condition %r is never updated inside the block — the "
                "loop would not terminate" % self.cond_var.name
            )
        x_names = carried + [n for n in ext_r if n not in carried]
        parent.append_op(
            type="while",
            inputs={"Condition": [self.cond_var.name], "X": x_names},
            outputs={"Out": list(carried)},
            attrs={
                "sub_block": sub,
                "carried_names": list(carried),
                "cond_name": self.cond_var.name,
                "x_names": list(x_names),
                "maximum_iterations": self.maximum_iterations or 0,
                "is_test": self.is_test,
            },
        )


class ConditionalBlock:
    """Run a block of ops when every scalar condition is true (reference
    layers/control_flow.py:1207 ConditionalBlock / conditional_block_op.cc).
    Vars assigned inside must already hold a value outside the block (the
    false path keeps the prior value)."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        for c in inputs:
            if not isinstance(c, Variable):
                raise TypeError("ConditionalBlock inputs must be Variables")
        self.conds = list(inputs)
        self.helper = LayerHelper("conditional_block", name=name)
        self._main = default_main_program()
        self._sub = None

    @contextlib.contextmanager
    def block(self):
        self._sub = self._main._create_block()
        try:
            yield
        finally:
            self._main._rollback()
        self._complete()

    def _complete(self):
        sub = self._sub
        parent = sub.parent_block
        ext_r, written = _external_reads_writes(sub)
        cond_names = [c.name for c in self.conds]
        x_names = written + [
            n for n in ext_r if n not in written and n not in cond_names
        ]
        parent.append_op(
            type="conditional_block",
            inputs={"Cond": cond_names, "X": x_names},
            outputs={"Out": list(written)},
            attrs={
                "sub_block": sub,
                "written_names": list(written),
                "x_names": list(x_names),
            },
        )


class Switch:
    """switch/case over scalar conditions (reference layers/control_flow.py:1290
    — the learning-rate-schedule workhorse). Each case runs iff its condition
    holds and no earlier case matched; default runs when none matched."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._matched = None  # bool var: any earlier case fired

    @contextlib.contextmanager
    def case(self, condition):
        if self._matched is None:
            eff = condition
            self._matched = condition
        else:
            not_prev = logical_not(self._matched)
            eff = logical_and(condition, not_prev)
            self._matched = logical_or(self._matched, condition)
        cb = ConditionalBlock([eff])
        with cb.block():
            yield

    @contextlib.contextmanager
    def default(self):
        if self._matched is None:
            raise ValueError("Switch.default() requires at least one case first")
        eff = logical_not(self._matched)
        cb = ConditionalBlock([eff])
        with cb.block():
            yield


class IfElse:
    """Batch-wise two-way branch (reference layers/control_flow.py:1066 IfElse
    splits rows by a (batch, 1) bool mask, runs each branch on its subset, and
    merges). TPU-first redesign: both branches compute over the FULL batch in
    the enclosing computation and ``()`` merges row-wise with a masked select —
    static shapes, XLA-fusable, numerically identical for elementwise-per-row
    branches (the reference's supported use)."""

    def __init__(self, cond, name=None):
        self.cond = cond
        self._in_true = None
        self._true_outs = []
        self._false_outs = []

    @contextlib.contextmanager
    def true_block(self):
        self._in_true = True
        try:
            yield
        finally:
            self._in_true = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        try:
            yield
        finally:
            self._in_true = None

    def input(self, x):
        if self._in_true is None:
            raise ValueError("IfElse.input() must be called inside a branch")
        return x

    def output(self, *outs):
        if self._in_true is None:
            raise ValueError("IfElse.output() must be called inside a branch")
        (self._true_outs if self._in_true else self._false_outs).extend(outs)

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError(
                "IfElse branches produced %d vs %d outputs"
                % (len(self._true_outs), len(self._false_outs))
            )
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            helper = LayerHelper("ifelse_merge")
            out = helper.create_variable_for_type_inference(t.dtype)
            helper.append_op(
                type="where",
                inputs={
                    "Condition": [self.cond.name],
                    "X": [t.name],
                    "Y": [f.name],
                },
                outputs={"Out": [out.name]},
            )
            merged.append(out)
        return merged if len(merged) != 1 else merged[0]


# ---------------------------------------------------------------------------
# recurrent networks (scan-based; ops/control_flow_ops.py "recurrent")
# ---------------------------------------------------------------------------


class _RNNBase:
    def __init__(self, layer_type, time_major, name=None):
        self.helper = LayerHelper(layer_type, name=name)
        self._main = default_main_program()
        self._time_major = time_major
        self._sub = None
        self._seq = []  # (outer var, inner var)
        self._mems = []  # dict(pre=Variable, boot=Variable, new=name|None)
        self._outs = []  # inner Variables
        self._seqlen = None
        self._completed = False
        self._outer_outs = None

    @contextlib.contextmanager
    def _block_ctx(self):
        self._sub = self._main._create_block()
        try:
            yield
        finally:
            self._main._rollback()
        self._complete()

    def _step_input(self, x, inner_shape):
        inner = self._sub.create_var(
            name=unique_name.generate(self.helper.name + "_step_in"),
            shape=list(inner_shape),
            dtype=x.dtype,
        )
        self._seq.append((x, inner))
        return inner

    def _in_parent(self):
        """Context: temporarily emit ops into the parent block (for boot-state
        creation, like the reference's StaticRNN memory boot ops)."""
        main = self._main

        @contextlib.contextmanager
        def ctx():
            saved = main.current_block_idx
            main.current_block_idx = self._sub.parent_idx
            try:
                yield
            finally:
                main.current_block_idx = saved

        return ctx()

    def _memory(self, init, shape, value, batch_ref, ref_batch_dim_idx, dtype):
        from . import tensor as tensor_layers

        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "memory() needs either init= or (shape= and a prior "
                    "step_input for the batch reference)"
                )
            with self._in_parent():
                boot = tensor_layers.fill_constant_batch_size_like(
                    input=batch_ref,
                    shape=[-1] + list(shape),
                    dtype=dtype,
                    value=value,
                    input_dim_idx=ref_batch_dim_idx,
                    output_dim_idx=0,
                )
        else:
            boot = init
        pre = self._sub.create_var(
            name=unique_name.generate(self.helper.name + "_mem_pre"),
            shape=list(boot.shape),
            dtype=boot.dtype,
        )
        self._mems.append({"pre": pre, "boot": boot, "new": None})
        return pre

    def update_memory(self, mem, new):
        for m in self._mems:
            if m["pre"].name == mem.name:
                m["new"] = new.name
                return
        raise ValueError("update_memory: %r is not a memory of this RNN" % mem.name)

    def _step_output(self, o):
        self._outs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self._step_output(o)

    def _complete(self):
        sub = self._sub
        parent = sub.parent_block
        for m in self._mems:
            if m["new"] is None:
                raise ValueError(
                    "memory %r was never update_memory()'d" % m["pre"].name
                )
        ext_r, _ = _external_reads_writes(sub)
        boot_names = [m["boot"].name for m in self._mems]
        closure = [n for n in ext_r if n not in boot_names]

        outer_outs, final_outs = [], []
        t_extent = None
        if self._seq:
            ov = self._seq[0][0]
            t_extent = ov.shape[0] if self._time_major else ov.shape[1]
        for o in self._outs:
            oshape = list(o.shape or ())
            stacked = (
                [t_extent] + oshape if self._time_major
                else oshape[:1] + [t_extent] + oshape[1:]
            )
            ov = parent.create_var(
                name=unique_name.generate(self.helper.name + "_out"),
                shape=stacked,
                dtype=o.dtype,
            )
            if self._seqlen is not None:
                # padded output keeps the ragged companion (layers/sequence.py
                # seq_len_of convention) so sequence ops chain off RNN outputs
                ov._len_name = self._seqlen.name
            outer_outs.append(ov)
        for m in self._mems:
            final_outs.append(
                parent.create_var(
                    name=unique_name.generate(self.helper.name + "_final"),
                    shape=list(m["boot"].shape or ()),
                    dtype=m["boot"].dtype,
                )
            )

        inputs = {
            "X": [ov.name for ov, _ in self._seq],
            "Boot": boot_names,
            "C": closure,
        }
        if self._seqlen is not None:
            inputs["SeqLen"] = [self._seqlen.name]
        parent.append_op(
            type="recurrent",
            inputs=inputs,
            outputs={
                "Out": [v.name for v in outer_outs],
                "FinalState": [v.name for v in final_outs],
            },
            attrs={
                "sub_block": sub,
                "x_names": [iv.name for _, iv in self._seq],
                "pre_state_names": [m["pre"].name for m in self._mems],
                "new_state_names": [m["new"] for m in self._mems],
                "out_names": [o.name for o in self._outs],
                "closure_names": list(closure),
                "time_major": self._time_major,
                "reverse": False,
            },
        )
        self._outer_outs = outer_outs
        self._final_outs = final_outs
        self._completed = True

    def _result(self):
        if not self._completed:
            raise ValueError("RNN block is not complete yet")
        outs = self._outer_outs
        return outs[0] if len(outs) == 1 else outs


class StaticRNN(_RNNBase):
    """Fixed-length RNN over time-major sequences (reference
    layers/control_flow.py:429; recurrent_op.cc). step_input slices dim 0 of a
    (T, B, ...) tensor; lowered to one lax.scan."""

    def __init__(self, name=None):
        super().__init__("static_rnn", time_major=True, name=name)

    def step(self):
        return self._block_ctx()

    def step_input(self, x):
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("StaticRNN.step_input needs a (T, B, ...) tensor")
        return self._step_input(x, x.shape[1:])

    def memory(
        self,
        init=None,
        shape=None,
        batch_ref=None,
        init_value=0.0,
        init_batch_dim_idx=0,
        ref_batch_dim_idx=1,
        dtype="float32",
    ):
        if batch_ref is None and self._seq:
            batch_ref = self._seq[0][0]
        return self._memory(
            init, shape, init_value, batch_ref, ref_batch_dim_idx, dtype
        )

    def step_output(self, o):
        self._step_output(o)

    def __call__(self, *args, **kwargs):
        return self._result()


class DynamicRNN(_RNNBase):
    """Variable-length RNN over padded batch-major sequences (reference
    layers/control_flow.py:1546, which compiles to lod_rank_table +
    lod_tensor_to_array + while_op with shrinking batches). TPU-first: one
    lax.scan over (B, T, ...) with a SeqLen vector; finished rows hold their
    state and output zeros — same results, static shapes."""

    def __init__(self, name=None):
        super().__init__("dynamic_rnn", time_major=False, name=name)

    def block(self):
        return self._block_ctx()

    def step_input(self, x, seq_len=None, level=0):
        if seq_len is not None:
            self._seqlen = seq_len
        if self._seqlen is None:
            raise ValueError(
                "DynamicRNN.step_input needs seq_len= on the first sequence "
                "input (padded-dense representation, SURVEY.md §5.7)"
            )
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("DynamicRNN.step_input needs a (B, T, ...) tensor")
        return self._step_input(x, x.shape[:1] + tuple(x.shape[2:]))

    def static_input(self, x):
        # non-sequence input, same every step: plain closure capture
        return x

    def memory(
        self,
        init=None,
        shape=None,
        value=0.0,
        need_reorder=False,
        dtype="float32",
    ):
        batch_ref = self._seq[0][0] if self._seq else None
        return self._memory(init, shape, value, batch_ref, 0, dtype)

    def __call__(self, *args, **kwargs):
        return self._result()


# ---------------------------------------------------------------------------
# tensor arrays
# ---------------------------------------------------------------------------


def create_array(dtype="float32", shape=None, name=None):
    """LOD_TENSOR_ARRAY variable (reference layers/control_flow.py:964).
    With shape=(capacity, ...) the buffer is pre-allocated, which is REQUIRED
    for arrays written inside While loops (fixed-shape carries)."""
    helper = LayerHelper("create_array", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    out.type = "lod_tensor_array"
    if shape is not None:
        helper.append_op(
            type="create_array",
            outputs={"Out": [out.name]},
            attrs={"shape": list(shape), "dtype": str(dtype)},
        )
        out.shape = tuple(shape)
    out._array_bound = shape is not None
    out._array_prealloc = shape is not None
    return out


def _static_int_value(v):
    """The build-time value of an integer Variable if it is produced by a
    single fill_constant and never rewritten (e.g. loop-free write indices);
    None otherwise."""
    producer, writes = None, 0
    for op in v.block.program.current_block().ops:
        if v.name in op.output_arg_names:
            writes += 1
            producer = op
    if writes == 1 and producer is not None and producer.type == "fill_constant":
        return int(producer.attrs.get("value", 0))
    return None


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    bound = getattr(array, "_array_bound", False)
    prealloc = getattr(array, "_array_prealloc", False)
    inputs = {"X": [x.name], "I": [i.name]}
    attrs = {}
    if prealloc:
        # fixed-capacity buffer (create_array(shape=...) / lod_tensor_to_array):
        # write in place, never grow — the form While-loop carries require
        inputs["Array"] = [array.name]
    else:
        static_i = _static_int_value(i)
        if static_i is None:
            raise ValueError(
                "array_write with a runtime-computed index needs a "
                "pre-allocated array — pass shape=(capacity, ...) to "
                "create_array (growable buffers track capacity statically)"
            )
        cap = getattr(array, "_array_cap", 0)
        if bound:
            inputs["Array"] = [array.name]
            attrs["grow_slots"] = max(0, static_i + 1 - cap)
        else:
            attrs["init_cap"] = static_i + 1
        array._array_cap = max(cap, static_i + 1)
    helper.append_op(
        type="write_to_array",
        inputs=inputs,
        outputs={"Out": [array.name]},
        attrs=attrs,
    )
    array._array_bound = True
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array.name], "I": [i.name]},
        outputs={"Out": [out.name]},
    )
    if array.shape and len(array.shape) > 1:
        out.shape = tuple(array.shape[1:])
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="lod_array_length",
        inputs={"X": [array.name]},
        outputs={"Out": [out.name]},
    )
    out.shape = (1,)
    out.stop_gradient = True
    return out


def lod_tensor_to_array(x, table=None):
    helper = LayerHelper("lod_tensor_to_array")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.type = "lod_tensor_array"
    helper.append_op(
        type="lod_tensor_to_array",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
    )
    if x.shape and len(x.shape) >= 2:
        out.shape = (x.shape[1], x.shape[0]) + tuple(x.shape[2:])
    out._array_bound = True
    out._array_prealloc = True
    return out


def array_to_lod_tensor(x, table=None):
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="array_to_lod_tensor",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
    )
    if x.shape and len(x.shape) >= 2:
        out.shape = (x.shape[1], x.shape[0]) + tuple(x.shape[2:])
    return out


def lod_rank_table(x, level=0, seq_len=None):
    """Rank table over sequence lengths (reference layers/control_flow.py:742).
    In the padded-dense representation pass the SeqLen companion as seq_len
    (or x itself if x IS the length vector); returns descending-length row
    indices."""
    src = seq_len if seq_len is not None else x
    helper = LayerHelper("lod_rank_table")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="lod_rank_table",
        inputs={"X": [src.name]},
        outputs={"Out": [out.name]},
    )
    out.stop_gradient = True
    # remember the length vector so max_sequence_len(rank_table) can resolve
    # it — the table itself is a row permutation, not lengths
    out._seq_len_source = src
    return out


def max_sequence_len(rank_table=None, seq_len=None):
    if seq_len is not None:
        src = seq_len
    elif rank_table is not None and getattr(rank_table, "_seq_len_source", None) is not None:
        # the rank table is a permutation; max() of it would be B-1, not the
        # max length — resolve back to the length vector it was built from
        src = rank_table._seq_len_source
    elif rank_table is not None:
        raise ValueError(
            "max_sequence_len needs the sequence-length vector: pass seq_len=, "
            "or a rank_table produced by lod_rank_table() in this program"
        )
    else:
        raise ValueError("max_sequence_len requires rank_table or seq_len")
    helper = LayerHelper("max_sequence_len")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="max_sequence_len",
        inputs={"X": [src.name]},
        outputs={"Out": [out.name]},
    )
    out.shape = (1,)
    out.stop_gradient = True
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x.name], "RankTable": [rank_table.name]},
        outputs={"Out": [out.name]},
    )
    out.shape = x.shape
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="shrink_rnn_memory",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
    )
    out.shape = x.shape
    return out


def Print(
    input,
    first_n=-1,
    message=None,
    summarize=20,
    print_tensor_name=True,
    print_tensor_type=True,
    print_tensor_shape=True,
    print_tensor_lod=True,
    print_phase="both",
):
    """In-graph tensor printing (reference print_op.cc); forwards its input."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "message": message or input.name,
            "summarize": summarize,
        },
    )
    out.shape = input.shape
    return out
