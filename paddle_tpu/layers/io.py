"""Data-layer entry points (reference python/paddle/fluid/layers/io.py:39
`data`, :633 `py_reader`, read_file, double_buffer)."""

from .. import framework, unique_name
from ..framework import VarType

__all__ = ["data", "py_reader", "read_file", "double_buffer", "batch", "shuffle"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=VarType.LOD_TENSOR,
    stop_gradient=True,
):
    """Declare a feed variable (reference layers/io.py:39). With
    append_batch_size the leading dim is -1 and resolved at feed time via the
    executor's shape-keyed compile cache."""
    helper_block = framework.default_main_program().current_block()
    shape = list(shape)
    if lod_level and lod_level > 0:
        # padded ragged field: (batch, time, *shape) — reference LoD tensors
        # are packed (T_total, *shape); the padded form adds the batch dim.
        # With append_batch_size=False the user's shape already leads with the
        # batch dim, so the time dim is inserted after it.
        shape = [-1, -1] + shape if append_batch_size else shape[:1] + [-1] + shape[1:]
    elif append_batch_size:
        shape = [-1] + shape
    v = helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )
    if lod_level and lod_level > 0:
        # ragged field: companion per-sample length vector fed alongside
        # (the TPU-native LoD representation — SURVEY.md §5.7); DataFeeder
        # produces `<name>@LEN` automatically.
        lv = helper_block.create_var(
            name=name + "@LEN",
            shape=[-1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
        v._len_name = lv.name
    return v


class GraphPyReader:
    """The graph-side handle returned by layers.py_reader (reference
    layers/io.py:633): owns the feed variables and the async device-prefetch
    queue; the Executor pulls staged batches from it when run() gets no feed."""

    def __init__(self, capacity, shapes, dtypes, lod_levels=None, name=None,
                 use_double_buffer=True):
        from ..py_reader import PyReader

        program = framework.default_main_program()
        name = name or unique_name.generate("py_reader")
        self.name = name
        lod_levels = lod_levels or [0] * len(shapes)
        self.vars = []
        for i, (shape, dtype, lod) in enumerate(zip(shapes, dtypes, lod_levels)):
            v = program.current_block().create_var(
                name="%s_slot_%d" % (name, i),
                shape=list(shape),
                dtype=dtype,
                lod_level=lod,
                is_data=True,
                stop_gradient=True,
            )
            self.vars.append(v)
        self._impl = PyReader(
            [v.name for v in self.vars],
            capacity=capacity,
            return_device_arrays=use_double_buffer,
        )
        readers = getattr(program, "_py_readers", None)
        if readers is None:
            readers = program._py_readers = []
        readers.append(self)

    # delegate lifecycle to the async impl (num_workers: the native data
    # runtime — multiprocess decode + shm ring + device double-buffer;
    # docs/data.md)
    def decorate_paddle_reader(self, reader, places=None, num_workers=None,
                               num_shards=None):
        from ..data_feeder import DataFeeder

        self._impl.set_feeder(DataFeeder(self.vars))
        self._impl.decorate_paddle_reader(
            reader, num_workers=num_workers, num_shards=num_shards
        )
        self._impl._batched_tuples = False  # the DataFeeder assembles rows
        return self

    def decorate_tensor_provider(self, reader, num_workers=None,
                                 num_shards=None):
        return self._impl.decorate_tensor_provider(
            reader, num_workers=num_workers, num_shards=num_shards
        )

    def decorate_batch_generator(self, reader, places=None, num_workers=None,
                                 num_shards=None):
        return self._impl.decorate_batch_generator(
            reader, num_workers=num_workers, num_shards=num_shards
        )

    def set_device_sharding(self, sharding):
        return self._impl.set_device_sharding(sharding)

    def push_back(self, batch):
        return self._impl.push_back(batch)

    def start(self):
        return self._impl.start()

    def reset(self):
        return self._impl.reset()

    def close(self):
        return self._impl.close()

    def next_batch(self):
        return self._impl.next_batch()

    @property
    def started(self):
        return self._impl._started

    # the executor's deferred-EOF flag (executor._pull_reader_steps) must
    # live on the impl so start()/reset() clear it with the epoch state
    @property
    def _eof_deferred(self):
        return self._impl._eof_deferred

    @_eof_deferred.setter
    def _eof_deferred(self, value):
        self._impl._eof_deferred = value


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    return GraphPyReader(capacity, shapes, dtypes, lod_levels, name,
                         use_double_buffer)


def read_file(reader):
    """Unpack a py_reader's slots into variables (reference layers/io.py
    read_file → read_op)."""
    if len(reader.vars) == 1:
        return reader.vars[0]
    return list(reader.vars)


def double_buffer(reader, place=None, name=None):
    """compat: prefetch-to-device is built into py_reader already"""
    return reader


def batch(reader, batch_size):
    """compat alias for paddle.batch on a reader creator"""
    from ..batch import batch as _batch

    return _batch(reader, batch_size)


def shuffle(reader, buffer_size):
    from .. import reader as reader_mod

    return reader_mod.shuffle(reader, buffer_size)
