"""Data-layer entry points (reference python/paddle/fluid/layers/io.py:39
`data`, :633 `py_reader`)."""

from .. import framework
from ..framework import VarType

__all__ = ["data"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=VarType.LOD_TENSOR,
    stop_gradient=True,
):
    """Declare a feed variable (reference layers/io.py:39). With
    append_batch_size the leading dim is -1 and resolved at feed time via the
    executor's shape-keyed compile cache."""
    helper_block = framework.default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )
