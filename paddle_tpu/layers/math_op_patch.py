"""Operator-overloading support for Variable (reference
python/paddle/fluid/layers/math_op_patch.py — monkey_patch_variable). Called
from framework.Variable's dunder methods."""

from ..framework import Variable
from ..layer_helper import LayerHelper

_SCALAR_SCALE = {"elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div"}


def binary_op(x, other, op_type, reverse=False):
    helper = LayerHelper(op_type)
    if not isinstance(other, Variable):
        val = float(other)
        if op_type in _SCALAR_SCALE and not reverse:
            # scalar fast path as a scale op (reference math_op_patch scale)
            attrs = {
                "elementwise_add": lambda: {"scale": 1.0, "bias": val},
                "elementwise_sub": lambda: {"scale": 1.0, "bias": -val},
                "elementwise_mul": lambda: {"scale": val, "bias": 0.0},
                "elementwise_div": lambda: {"scale": 1.0 / val, "bias": 0.0},
            }[op_type]()
            out = helper.create_variable_for_type_inference(x.dtype)
            helper.append_op(
                type="scale",
                inputs={"X": [x.name]},
                outputs={"Out": [out.name]},
                attrs=attrs,
            )
            return out
        # materialize scalar as a [1] tensor and broadcast
        const = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            type="fill_constant",
            outputs={"Out": [const.name]},
            attrs={"shape": [1], "dtype": x.dtype, "value": val},
        )
        other = const
    a, b = (other, x) if reverse else (x, other)
    out_dtype = x.dtype
    if op_type in ("less_than", "less_equal", "greater_than", "greater_equal", "equal", "not_equal"):
        out_dtype = "bool"
    out = helper.create_variable_for_type_inference(out_dtype)
    helper.append_op(
        type=op_type,
        inputs={"X": [a.name], "Y": [b.name]},
        outputs={"Out": [out.name]},
        attrs={"axis": -1},
    )
    return out
