"""Neural-network layers (reference python/paddle/fluid/layers/nn.py — 144
public layers; this module covers the dense/conv/norm/embedding core, with
sequence and detection families in their own modules)."""

import numpy as np

from .. import framework
from ..framework import Variable
from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper

__all__ = [
    "flash_attention",
    "fc",
    "embedding",
    "hash",
    "chunk_eval",
    "dropout",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "softmax",
    "softmax_with_cross_entropy",
    "cross_entropy",
    "square_error_cost",
    "smooth_l1",
    "log_loss",
    "sigmoid_cross_entropy_with_logits",
    "matmul",
    "mul",
    "topk",
    "reshape",
    "transpose",
    "split",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "mean",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "one_hot",
    "lrn",
    "pad",
    "pad2d",
    "label_smooth",
    "flatten",
    "squeeze",
    "unsqueeze",
    "stack",
    "unstack",
    "expand",
    "gather",
    "scatter",
    "slice",
    "shape",
    "clip",
    "clip_by_norm",
    "prelu",
    "leaky_relu",
    "relu",
    "log",
    "l2_normalize",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "autoincreased_step_counter",
    "ring_attention",
    "distributed_embedding",
    "beam_search",
    "beam_search_decode",
    "kv_cache_write",
    "paged_attention",
]


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    is_test=False,
    name=None,
):
    """Fully-connected layer (reference layers/nn.py fc: one mul op per input
    + sum + bias + activation, composed from `mul`)."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    all_inputs = helper.multiple_input()
    if num_flatten_dims == 1 and len(all_inputs) > 1:
        # mixed ragged/dense inputs would produce rank-mismatched mul results
        out_ranks = {
            (len(v.shape) if getattr(v, "_len_name", None) else 2)
            for v in all_inputs
        }
        if len(out_ranks) > 1:
            raise ValueError(
                "fc with mixed ragged and non-ragged inputs is ambiguous; "
                "pass an explicit num_flatten_dims"
            )
    mul_results = []
    for input_var, param_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        nfd = num_flatten_dims
        # ragged input: reference LoD tensors are (T_total, d) so fc's default
        # num_flatten_dims=1 means "per timestep"; our padded (b, t, d) needs
        # the feature dim alone flattened for the same semantics
        if getattr(input_var, "_len_name", None) and num_flatten_dims == 1:
            nfd = len(input_shape) - 1
        param_shape = [
            int(np.prod(input_shape[nfd:])),
            size,
        ]
        w = helper.create_parameter(
            attr=param_attr, shape=param_shape, dtype=dtype, is_bias=False
        )
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [input_var.name], "Y": [w.name]},
            outputs={"Out": [tmp.name]},
            attrs={"x_num_col_dims": nfd, "y_num_col_dims": 1},
        )
        if getattr(input_var, "_len_name", None):
            tmp._len_name = input_var._len_name
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="sum",
            inputs={"X": [v.name for v in mul_results]},
            outputs={"Out": [pre_bias.name]},
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=nfd)
    out = helper.append_activation(pre_act)
    from .sequence import _propagate

    return _propagate(out, mul_results[0])


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """Embedding lookup (reference layers/nn.py embedding → lookup_table op).
    `is_sparse=True` routes the gradient through the SelectedRows analog
    (paddle_tpu/embedding/): a (rows, values) pair whose size scales with
    ids-per-batch, consumed by per-row sgd/adagrad/adam updates — use it for
    big tables touched sparsely. Dense gradients (the default) stay a single
    fused scatter-add. `is_distributed=True` row-shards the table over the
    mesh 'ep' axis via the EmbeddingEngine."""
    if is_distributed:
        return distributed_embedding(
            input,
            size,
            param_attr=param_attr,
            dtype=dtype,
            is_sparse=is_sparse,
            padding_idx=padding_idx,
        )
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False
    )
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1
        if padding_idx is None
        else padding_idx
        if padding_idx >= 0
        else (size[0] + padding_idx)
    )
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w.name], "Ids": [input.name]},
        outputs={"Out": [tmp.name]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": padding_idx,
        },
    )
    if getattr(input, "_len_name", None):
        tmp._len_name = input._len_name
    return tmp


def hash(input, hash_size, num_hash=1, name=None):
    """Feature-hash integer ids into [0, hash_size) buckets (reference
    layers/nn.py hash → hash op): Out is [N, num_hash, 1], one bucket id per
    hash seed, ready to feed `embedding`/lookup_table. See ops/core_ops.py
    _hash for the XXH32 scheme."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="hash",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"num_hash": num_hash, "mod_by": hash_size},
    )
    out.stop_gradient = True
    return out


def chunk_eval(
    input,
    label,
    chunk_scheme,
    num_chunk_types,
    excluded_chunk_types=None,
    seq_length=None,
):
    """Chunk-level precision/recall/F1 for sequence tagging (reference
    layers/nn.py chunk_eval → chunk_eval op, the conlleval metric).

    input/label are padded-dense [b, t] tag grids (this repo's sequence
    convention), with `seq_length` [b] masking padding. Returns the 6-tuple
    (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks); fetch the three counts per batch and feed them to
    fluid.metrics.ChunkEvaluator.update for streaming aggregation — the
    counting itself runs in-framework, inside the compiled program."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference(dtype="float32")
    recall = helper.create_variable_for_type_inference(dtype="float32")
    f1_score = helper.create_variable_for_type_inference(dtype="float32")
    num_infer = helper.create_variable_for_type_inference(dtype="int64")
    num_label = helper.create_variable_for_type_inference(dtype="int64")
    num_correct = helper.create_variable_for_type_inference(dtype="int64")
    inputs = {"Inference": [input.name], "Label": [label.name]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length.name]
    helper.append_op(
        type="chunk_eval",
        inputs=inputs,
        outputs={
            "Precision": [precision.name],
            "Recall": [recall.name],
            "F1-Score": [f1_score.name],
            "NumInferChunks": [num_infer.name],
            "NumLabelChunks": [num_label.name],
            "NumCorrectChunks": [num_correct.name],
        },
        attrs={
            "chunk_scheme": chunk_scheme,
            "num_chunk_types": num_chunk_types,
            "excluded_chunk_types": list(excluded_chunk_types or []),
        },
    )
    for v in (precision, recall, f1_score, num_infer, num_label, num_correct):
        v.stop_gradient = True
    return precision, recall, f1_score, num_infer, num_label, num_correct


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "Mask": [mask.name]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    """2-D convolution, NCHW / OIHW (reference layers/nn.py conv2d → conv2d op
    → cuDNN; here XLA conv_general_dilated targeting the MXU)."""
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size

    def _std(shape):
        fan_in = (num_channels // groups) * shape[2] * shape[3]
        return (2.0 / fan_in) ** 0.5

    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=Normal(0.0, _std(filter_shape)),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "use_cudnn": use_cudnn,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("filter_size or output_size required")
        output_size = _pair(output_size)
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h_in - 1) * stride[0] + 2 * padding[0] - 1) // dilation[0]
            + 1,
            (output_size[1] - (w_in - 1) * stride[1] + 2 * padding[1] - 1) // dilation[1]
            + 1,
        ]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    name=None,
    exclusive=True,
):
    helper = LayerHelper("pool2d", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type="pool2d",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "global_pooling": global_pooling,
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    in_place=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
    use_global_stats=False,
):
    """Batch normalization (reference layers/nn.py batch_norm → batch_norm op).
    Running mean/variance are persistable non-trainable params updated by the
    op itself (MeanOut/VarianceOut alias the same variables)."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    channels = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    param_shape = [channels]

    scale = helper.create_parameter(
        attr=helper.param_attr,
        shape=param_shape,
        dtype=dtype,
        default_initializer=Constant(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
    )
    from ..param_attr import ParamAttr

    mean = helper.create_parameter(
        attr=ParamAttr(
            name=moving_mean_name, initializer=Constant(0.0), trainable=False
        ),
        shape=param_shape,
        dtype=dtype,
    )
    variance = helper.create_parameter(
        attr=ParamAttr(
            name=moving_variance_name, initializer=Constant(1.0), trainable=False
        ),
        shape=param_shape,
        dtype=dtype,
    )
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = input if in_place else helper.create_variable_for_type_inference(dtype)

    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input.name],
            "Scale": [scale.name],
            "Bias": [bias.name],
            "Mean": [mean.name],
            "Variance": [variance.name],
        },
        outputs={
            "Y": [out.name],
            "MeanOut": [mean.name],
            "VarianceOut": [variance.name],
            "SavedMean": [saved_mean.name],
            "SavedVariance": [saved_variance.name],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    param_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr,
            shape=param_shape,
            dtype=dtype,
            default_initializer=Constant(1.0),
        )
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b.name]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out.name], "Mean": [mean_out.name], "Variance": [var_out.name]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def softmax(input, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="softmax", inputs={"X": [input.name]}, outputs={"Out": [out.name]}
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    smooth_eps=0.0,
):
    """smooth_eps (TPU-native extension, hard labels only): uniform label
    smoothing fused into the CE — mathematically identical to
    label_smooth(one_hot(label, V), ε) + soft_label CE, but never
    materializes the [N, V] one-hot (which dominates loss-path HBM traffic
    and memory at LM vocab sizes)."""
    if smooth_eps and soft_label:
        raise ValueError("smooth_eps applies to hard labels only")
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits.name], "Label": [label.name]},
        outputs={"Softmax": [softmax_out.name], "Loss": [loss.name]},
        attrs={
            "soft_label": soft_label,
            "ignore_index": ignore_index,
            "numeric_stable_mode": numeric_stable_mode,
            "smooth_eps": float(smooth_eps),
        },
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input.name], "Label": [label.name]},
        outputs={"Y": [out.name]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="square_error_cost",
        inputs={"X": [input.name], "Y": [label.name]},
        outputs={"Out": [out.name]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Diff": [diff.name], "Out": [loss.name]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input.name], "Labels": [label.name]},
        outputs={"Loss": [loss.name]},
        attrs={"epsilon": epsilon},
    )
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x.name], "Label": [label.name]},
        outputs={"Out": [out.name]},
        attrs={"ignore_index": ignore_index},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={
            "transpose_X": transpose_x,
            "transpose_Y": transpose_y,
            "alpha": float(alpha),
        },
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="top_k",
        inputs={"X": [input.name]},
        outputs={"Out": [values.name], "Indices": [indices.name]},
        attrs={"k": int(k)},
    )
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="reshape2",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"shape": [int(s) for s in shape]},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="transpose2",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = [int(s) for s in num_or_sections]
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in range(num or len(sections))
    ]
    helper.append_op(
        type="split",
        inputs={"X": [input.name]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"num": num, "sections": sections, "axis": dim},
    )
    return outs


def _reduce(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is not None and not isinstance(dim, (list, tuple)):
        dim = [dim]
    helper.append_op(
        type=op_type,
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "dim": dim if dim is not None else [0],
            "keep_dim": keep_dim,
            "reduce_all": dim is None,
        },
    )
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def _elementwise(op_type, x, y, axis, act, name):
    helper = LayerHelper(op_type, name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type=op_type,
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"axis": axis},
    )
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="one_hot",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"depth": depth},
    )
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="lrn",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name], "MidOut": [mid.name]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pad",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(
    input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0, data_format="NCHW", name=None
):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pad2d",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "paddings": list(paddings),
            "mode": mode,
            "pad_value": float(pad_value),
            "data_format": data_format,
        },
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label.name]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist.name]
    helper.append_op(
        type="label_smooth",
        inputs=inputs,
        outputs={"Out": [out.name]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="flatten2",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axis": axis},
    )
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="squeeze2",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="unsqueeze2",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name], "XShape": [xshape.name]},
        attrs={"axes": list(axes)},
    )
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    if isinstance(x, Variable):
        x = [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        type="stack",
        inputs={"X": [v.name for v in x]},
        outputs={"Y": [out.name]},
        attrs={"axis": axis},
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op(
        type="unstack",
        inputs={"X": [x.name]},
        outputs={"Y": [o.name for o in outs]},
        attrs={"axis": axis, "num": num},
    )
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather",
        inputs={"X": [input.name], "Index": [index.name]},
        outputs={"Out": [out.name]},
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input.name], "Ids": [index.name], "Updates": [updates.name]},
        outputs={"Out": [out.name]},
        attrs={"overwrite": overwrite},
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="slice",
        inputs={"Input": [input.name]},
        outputs={"Out": [out.name]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="shape", inputs={"Input": [input.name]}, outputs={"Out": [out.name]}
    )
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="clip",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="clip_by_norm",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    alpha_shape = [1]
    if mode == "channel":
        alpha_shape = [x.shape[1]]
    elif mode == "element":
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        attr=helper.param_attr,
        shape=alpha_shape,
        dtype=x.dtype,
        default_initializer=Constant(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="prelu",
        inputs={"X": [x.name], "Alpha": [alpha.name]},
        outputs={"Out": [out.name]},
        attrs={"mode": mode},
    )
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="leaky_relu",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"alpha": float(alpha)},
    )
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="relu", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def log(x, name=None):
    helper = LayerHelper("log", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="log", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="norm",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name], "Norm": [norm.name]},
        attrs={"axis": 1 if axis is None else axis, "epsilon": epsilon},
    )
    return out


def image_resize(input, out_shape=None, scale=None, name=None, resample="BILINEAR", actual_shape=None, align_corners=True, align_mode=1):
    helper = LayerHelper("image_resize", name=name)
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="bilinear_interp" if resample == "BILINEAR" else "nearest_interp",
        inputs={"X": [input.name]},
        outputs={"Out": [out.name]},
        attrs={
            "out_h": int(out_shape[0]),
            "out_w": int(out_shape[1]),
            "align_corners": align_corners,
        },
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None, actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR", actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None, actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST", actual_shape, align_corners)


def ring_attention(q, k, v, causal=False, axis_name="sp", name=None):
    """Exact attention with sequence sharded over the mesh's `axis_name`
    (context parallelism — new TPU-native capability; see
    parallel/ring_attention.py). q/k/v: (b, heads, t, d)."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(
        type="ring_attention",
        inputs={"Q": [q.name], "K": [k.name], "V": [v.name]},
        outputs={"Out": [out.name]},
        attrs={"causal": causal, "axis_name": axis_name},
    )
    return out


def distributed_embedding(
    input,
    size,
    param_attr=None,
    dtype="float32",
    axis_name="ep",
    is_sparse=True,
    padding_idx=None,
    name=None,
):
    """Row-sharded embedding (the reference's distributed lookup table,
    SURVEY.md §2.7.5) on the EmbeddingEngine (paddle_tpu/embedding/): the
    table param shards over `axis_name`, the forward is a local gather + one
    psum, and with `is_sparse` (default) the backward emits a SelectedRows
    pair consumed by per-row optimizer updates with row-sharded moments —
    wire/HBM cost O(ids-per-batch) instead of O(table rows)."""
    from ..embedding import EmbeddingEngine

    engine = EmbeddingEngine(
        name=name,
        num_rows=size[0],
        dim=size[1],
        dtype=dtype,
        axis_name=axis_name,
        padding_idx=padding_idx,
        is_sparse=is_sparse,
        param_attr=param_attr,
    )
    return engine.lookup(input)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter (reference layers/nn.py autoincreased_step_counter):
    persistable int var incremented once per executor run; used by LR
    schedulers."""
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name=counter_name, dtype="int32", shape=[1], persistable=True
    )
    if not getattr(counter, "_step_counter_initialized", False):
        helper.set_variable_initializer(
            counter, Constant(value=float(begin - 1))
        )
        helper.main_program.global_block()._prepend_op(
            type="increment",
            inputs={"X": [counter.name]},
            outputs={"Out": [counter.name]},
            attrs={"step": float(step)},
        )
        counter._step_counter_initialized = True
        counter.stop_gradient = True
    return counter


def beam_search(
    pre_ids,
    pre_scores,
    ids,
    scores,
    beam_size,
    end_id,
    level=0,
    name=None,
    return_parent_idx=False,
):
    """One beam-search expansion step (reference layers/nn.py beam_search →
    beam_search_op.cc). Dense [batch*beam] layout: instead of the reference's
    LoD-encoded parentage this also produces a flat parent_idx tensor —
    gather decoder state with it each step (selected_ids._parent_idx holds
    the Variable when return_parent_idx is False).

    First step: initialize pre_scores as [0, -inf, ..., -inf] per source so
    identical initial beams don't crowd the beam (see decode_ops.py)."""
    helper = LayerHelper("beam_search", **locals())
    selected_ids = helper.create_variable_for_type_inference("int64")
    selected_scores = helper.create_variable_for_type_inference("float32")
    parent_idx = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="beam_search",
        inputs={
            "pre_ids": [pre_ids.name],
            "pre_scores": [pre_scores.name],
            "ids": [ids.name],
            "scores": [scores.name],
        },
        outputs={
            "selected_ids": [selected_ids.name],
            "selected_scores": [selected_scores.name],
            "parent_idx": [parent_idx.name],
        },
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level},
    )
    selected_ids.stop_gradient = True
    selected_scores.stop_gradient = True
    parent_idx.stop_gradient = True
    selected_ids._parent_idx = parent_idx
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None, parents=None):
    """Backtrack per-step beam selections into full hypotheses (reference
    layers/nn.py beam_search_decode → beam_search_decode_op.cc). `ids` and
    `scores` are tensor arrays written once per step; pass the parents array
    (of beam_search parent_idx writes) to follow beam reordering. Returns
    (sentence_ids [B, beam, T] best-first, sentence_scores [B, beam]); the
    ids Variable carries per-hypothesis lengths in ._hyp_len."""
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_variable_for_type_inference("int64")
    sentence_scores = helper.create_variable_for_type_inference("float32")
    hyp_len = helper.create_variable_for_type_inference("int32")
    inputs = {"Ids": [ids.name], "Scores": [scores.name]}
    if parents is not None:
        inputs["Parents"] = [parents.name]
    helper.append_op(
        type="beam_search_decode",
        inputs=inputs,
        outputs={
            "SentenceIds": [sentence_ids.name],
            "SentenceScores": [sentence_scores.name],
            "SentenceLength": [hyp_len.name],
        },
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    sentence_ids.stop_gradient = True
    sentence_scores.stop_gradient = True
    hyp_len.stop_gradient = True
    sentence_ids._hyp_len = hyp_len
    return sentence_ids, sentence_scores


def flash_attention(q, k, v, causal=False, sm_scale=None, name=None):
    """Fused blockwise attention over (b, h, t, d) tensors — emits the
    Pallas flash-attention op (ops/pallas_kernels.py), the hand-tuned-kernel
    tier analog of the reference's math/jit_kernel fused primitives."""
    from ..ops.pallas_kernels import flash_path_taken

    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    attrs = {"causal": bool(causal)}
    if sm_scale is not None:
        attrs["sm_scale"] = float(sm_scale)
    outputs = {"Out": [out.name]}
    # declare the logsumexp residual exactly when the static shapes make the
    # lowering take the Pallas path (flash_path_taken is that decision's
    # mirror), so flash_attention_grad consumes the saved residual instead
    # of re-running the forward inside jax.vjp (see _flash_attention_op)
    tq = q.shape[2] if q.shape is not None and len(q.shape) == 4 else -1
    tk = k.shape[2] if k.shape is not None and len(k.shape) == 4 else -1
    if flash_path_taken(tq, tk, causal=bool(causal)):
        lse = helper.create_variable_for_type_inference("float32")
        lse.stop_gradient = True
        outputs["Lse"] = [lse.name]
    helper.append_op(
        type="flash_attention",
        inputs={"Q": [q.name], "K": [k.name], "V": [v.name]},
        outputs=outputs,
        attrs=attrs,
    )
    return out


def kv_cache_write(pool, rows, block_table, pos, page_size, scales=None,
                   name=None):
    """Scatter per-token K or V rows into a paged cache pool in place.

    ``pool`` is a persistable ``[n_pages * page_size, feat]`` tensor; each
    row of ``rows`` lands at ``block_table[pos // page_size] * page_size +
    pos % page_size``. The op's output IS the pool variable (the in-place
    idiom), so the serving lowering classifies the pool as written state
    and can donate its buffer across decode steps.

    ``scales`` (a persistable ``[n_pages * page_size]`` f32 tensor) turns
    on the int8 storage mode: rows quantize symmetrically per row on the
    scatter and the scale pool becomes a second in-place output, donated
    alongside the level pool."""
    helper = LayerHelper("kv_cache_write", name=name)
    inputs = {
        "Pool": [pool.name],
        "Rows": [rows.name],
        "BlockTable": [block_table.name],
        "Pos": [pos.name],
    }
    outputs = {"Out": [pool.name]}
    if scales is not None:
        inputs["Scales"] = [scales.name]
        outputs["OutScales"] = [scales.name]
    helper.append_op(
        type="kv_cache_write",
        inputs=inputs,
        outputs=outputs,
        attrs={"page_size": int(page_size)},
    )
    return pool


def paged_attention(q, k_pool, v_pool, block_table, pos, n_head, page_size,
                    sm_scale=None, k_scales=None, v_scales=None, name=None):
    """One-query-per-slot attention over a paged KV pool.

    ``q`` is ``[slots, n_head * d_head]`` (one decode token per slot),
    ``block_table`` ``[slots, pages_per_slot]`` int32, ``pos`` the query
    token's position; each slot attends to context positions 0..pos through
    its block table. Unused table entries point at the scratch page and are
    masked by the position bound. ``k_scales``/``v_scales`` (both or
    neither) read int8 pools: per-row f32 scales dequantize the gathered
    levels inline (see ops/generation_ops.py int8 pool mode)."""
    helper = LayerHelper("paged_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    attrs = {"n_head": int(n_head), "page_size": int(page_size)}
    if sm_scale is not None:
        attrs["sm_scale"] = float(sm_scale)
    inputs = {
        "Q": [q.name],
        "KPool": [k_pool.name],
        "VPool": [v_pool.name],
        "BlockTable": [block_table.name],
        "Pos": [pos.name],
    }
    if k_scales is not None:
        inputs["KScales"] = [k_scales.name]
        inputs["VScales"] = [v_scales.name]
    helper.append_op(
        type="paged_attention",
        inputs=inputs,
        outputs={"Out": [out.name]},
        attrs=attrs,
    )
    return out
