"""Detection layers (reference python/paddle/fluid/layers/detection.py:
prior_box, density_prior_box, anchor_generator, box_coder, iou_similarity,
bipartite_match, target_assign, multiclass_nms→detection_output, ssd_loss,
multi_box_head, roi_pool/roi_align wrappers, polygon_box_transform,
generate_proposals, yolov3 loss).

Variable-count outputs are fixed-capacity (-1 padded) with a count companion
instead of LoD (ops/detection_ops.py)."""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from .sequence import _new_len_var, seq_len_of

__all__ = [
    "prior_box",
    "density_prior_box",
    "anchor_generator",
    "box_coder",
    "iou_similarity",
    "bipartite_match",
    "target_assign",
    "multiclass_nms",
    "detection_output",
    "ssd_loss",
    "multi_box_head",
    "roi_pool",
    "roi_align",
    "polygon_box_transform",
    "generate_proposals",
    "yolov3_loss",
]


def prior_box(
    input,
    image,
    min_sizes,
    max_sizes=None,
    aspect_ratios=(1.0,),
    variance=(0.1, 0.1, 0.2, 0.2),
    flip=False,
    clip=False,
    steps=(0.0, 0.0),
    offset=0.5,
    name=None,
    min_max_aspect_ratios_order=False,
):
    helper = LayerHelper("prior_box", **locals())
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
            "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
        },
    )
    boxes.stop_gradient = True
    variances.stop_gradient = True
    return boxes, variances


def density_prior_box(
    input,
    image,
    densities,
    fixed_sizes,
    fixed_ratios=(1.0,),
    variance=(0.1, 0.1, 0.2, 0.2),
    clip=False,
    steps=(0.0, 0.0),
    offset=0.5,
    name=None,
):
    helper = LayerHelper("density_prior_box", **locals())
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [boxes.name], "Variances": [variances.name]},
        attrs={
            "densities": list(densities),
            "fixed_sizes": list(fixed_sizes),
            "fixed_ratios": list(fixed_ratios),
            "variances": list(variance),
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    boxes.stop_gradient = True
    variances.stop_gradient = True
    return boxes, variances


def anchor_generator(
    input,
    anchor_sizes,
    aspect_ratios,
    variance=(0.1, 0.1, 0.2, 0.2),
    stride=None,
    offset=0.5,
    name=None,
):
    helper = LayerHelper("anchor_generator", **locals())
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="anchor_generator",
        inputs={"Input": [input.name]},
        outputs={"Anchors": [anchors.name], "Variances": [variances.name]},
        attrs={
            "anchor_sizes": list(anchor_sizes),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "stride": list(stride or [16.0, 16.0]),
            "offset": offset,
        },
    )
    anchors.stop_gradient = True
    variances.stop_gradient = True
    return anchors, variances


def box_coder(
    prior_box,
    prior_box_var,
    target_box,
    code_type="encode_center_size",
    box_normalized=True,
    name=None,
):
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out.name]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity",
        inputs={"X": [x.name], "Y": [y.name]},
        outputs={"Out": [out.name]},
        attrs={"box_normalized": box_normalized},
    )
    return out


def bipartite_match(
    dist_matrix, match_type="bipartite", dist_threshold=0.5, name=None
):
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_variable_for_type_inference("int32")
    match_dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix.name]},
        outputs={
            "ColToRowMatchIndices": [match_indices.name],
            "ColToRowMatchDist": [match_dist.name],
        },
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    match_indices.stop_gradient = True
    match_dist.stop_gradient = True
    return match_indices, match_dist


def target_assign(
    input, matched_indices, negative_indices=None, mismatch_value=0, name=None
):
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input.name], "MatchIndices": [matched_indices.name]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices.name]
    helper.append_op(
        type="target_assign",
        inputs=inputs,
        outputs={"Out": [out.name], "OutWeight": [out_weight.name]},
        attrs={"mismatch_value": mismatch_value},
    )
    return out, out_weight


def multiclass_nms(
    bboxes,
    scores,
    score_threshold,
    nms_top_k,
    keep_top_k,
    nms_threshold=0.3,
    normalized=True,
    nms_eta=1.0,
    background_label=0,
    name=None,
):
    """Returns [B, keep_top_k, 6] (-1 padded) with a count companion
    (reference multiclass_nms_op.cc emitted LoD)."""
    helper = LayerHelper("multiclass_nms", **locals())
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    len_name = _new_len_var(helper, out)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes.name], "Scores": [scores.name]},
        outputs={"Out": [out.name], "OutLen": [len_name]},
        attrs={
            "background_label": background_label,
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "nms_threshold": nms_threshold,
            "keep_top_k": keep_top_k,
            "normalized": normalized,
        },
    )
    out.stop_gradient = True
    return out


def detection_output(
    loc,
    scores,
    prior_box,
    prior_box_var,
    background_label=0,
    nms_threshold=0.3,
    nms_top_k=400,
    keep_top_k=200,
    score_threshold=0.01,
    nms_eta=1.0,
):
    """Decode + NMS (reference layers/detection.py detection_output). `loc`
    [B, M, 4] deltas, `scores` [B, M, C] post-softmax."""
    from .nn import transpose

    decoded = box_coder(
        prior_box, prior_box_var, loc, code_type="decode_center_size"
    )  # [B, M, 4]
    scores_t = transpose(scores, [0, 2, 1])  # [B, C, M]
    return multiclass_nms(
        decoded,
        scores_t,
        score_threshold=score_threshold,
        nms_top_k=nms_top_k,
        keep_top_k=keep_top_k,
        nms_threshold=nms_threshold,
        background_label=background_label,
    )


def ssd_loss(
    location,
    confidence,
    gt_box,
    gt_label,
    prior_box,
    prior_box_var=None,
    background_label=0,
    overlap_threshold=0.5,
    neg_pos_ratio=3.0,
    neg_overlap=0.5,
    loc_loss_weight=1.0,
    conf_loss_weight=1.0,
    match_type="per_prediction",
    mining_type="max_negative",
    normalize=True,
    sample_size=None,
):
    """Fused SSD loss (see ops/detection_ops.py _ssd_loss). gt_box/gt_label
    are padded [B, G, ...] with gt_box carrying the @LEN companion."""
    helper = LayerHelper("ssd_loss", **locals())
    loss = helper.create_variable_for_type_inference("float32")
    inputs = {
        "Location": [location.name],
        "Confidence": [confidence.name],
        "GTBox": [gt_box.name],
        "GTLabel": [gt_label.name],
        "GTLen": [seq_len_of(gt_box)],
        "PriorBox": [prior_box.name],
    }
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op(
        type="ssd_loss",
        inputs=inputs,
        outputs={"Loss": [loss.name]},
        attrs={
            "background_label": background_label,
            "overlap_threshold": overlap_threshold,
            "neg_pos_ratio": neg_pos_ratio,
            "loc_loss_weight": loc_loss_weight,
            "conf_loss_weight": conf_loss_weight,
            "match_type": match_type,
        },
    )
    return loss


def multi_box_head(
    inputs,
    image,
    base_size,
    num_classes,
    aspect_ratios,
    min_ratio=None,
    max_ratio=None,
    min_sizes=None,
    max_sizes=None,
    steps=None,
    step_w=None,
    step_h=None,
    offset=0.5,
    variance=(0.1, 0.1, 0.2, 0.2),
    flip=True,
    clip=False,
    kernel_size=1,
    pad=0,
    stride=1,
    name=None,
    min_max_aspect_ratios_order=False,
):
    """SSD heads over multiple feature maps (reference layers/detection.py
    multi_box_head): per map, conv for loc + conf, prior_box; concatenated to
    mbox_loc [B, M, 4], mbox_conf [B, M, C], boxes [M, 4], vars [M, 4]."""
    from . import nn, tensor

    if min_sizes is None:
        # reference ratio schedule (layers/detection.py:1082)
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        if num_layer > 2:
            step = int((max_ratio - min_ratio) / (num_layer - 2))
            for ratio in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * ratio / 100.0)
                max_sizes.append(base_size * (ratio + step) / 100.0)
            min_sizes = [base_size * 0.1] + min_sizes
            max_sizes = [base_size * 0.2] + max_sizes
        else:
            min_sizes = [base_size * 0.2, base_size * 0.5]
            max_sizes = [base_size * 0.5, base_size * 0.8]

    locs, confs, boxes_list, vars_list = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[0], (list, tuple)) else aspect_ratios
        step = steps[i] if steps else (step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0)
        if not isinstance(step, (list, tuple)):
            step = (step, step)
        box, var = prior_box(
            x, image,
            min_sizes=[mins] if not isinstance(mins, (list, tuple)) else mins,
            max_sizes=[maxs] if maxs and not isinstance(maxs, (list, tuple)) else maxs,
            aspect_ratios=ar, variance=variance, flip=flip, clip=clip,
            steps=step, offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order,
        )
        num_priors = box.shape[2] if box.shape else 0
        nb = num_priors * (box.shape[0] * box.shape[1])
        loc = nn.conv2d(x, num_filters=num_priors * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.conv2d(x, num_filters=num_priors * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        # NCHW -> [B, H*W*P, 4|C]
        loc = nn.transpose(loc, [0, 2, 3, 1])
        loc = nn.reshape(loc, [0, -1, 4])
        conf = nn.transpose(conf, [0, 2, 3, 1])
        conf = nn.reshape(conf, [0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_list.append(nn.reshape(box, [-1, 4]))
        vars_list.append(nn.reshape(var, [-1, 4]))

    mbox_loc = tensor.concat(locs, axis=1)
    mbox_conf = tensor.concat(confs, axis=1)
    all_boxes = tensor.concat(boxes_list, axis=0)
    all_vars = tensor.concat(vars_list, axis=0)
    return mbox_loc, mbox_conf, all_boxes, all_vars


def _roi_op(op_type, input, rois, pooled_height, pooled_width, spatial_scale,
            extra_attrs=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {
        "pooled_height": pooled_height,
        "pooled_width": pooled_width,
        "spatial_scale": spatial_scale,
    }
    attrs.update(extra_attrs or {})
    helper.append_op(
        type=op_type,
        inputs={
            "X": [input.name],
            "ROIs": [rois.name],
            "RoisLen": [seq_len_of(rois)],
        },
        outputs={"Out": [out.name]},
        attrs=attrs,
    )
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    """reference layers/nn.py roi_pool → roi_pool_op.h. `rois` is padded
    [B, R, 4] with a @LEN companion (reference used LoD batch mapping)."""
    return _roi_op("roi_pool", input, rois, pooled_height, pooled_width,
                   spatial_scale)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    """reference layers/nn.py roi_align → roi_align_op.h."""
    return _roi_op("roi_align", input, rois, pooled_height, pooled_width,
                   spatial_scale, {"sampling_ratio": sampling_ratio}, name)


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="polygon_box_transform",
        inputs={"Input": [input.name]},
        outputs={"Output": [out.name]},
    )
    return out


def generate_proposals(
    scores,
    bbox_deltas,
    im_info,
    anchors,
    variances,
    pre_nms_top_n=6000,
    post_nms_top_n=1000,
    nms_thresh=0.5,
    min_size=0.1,
    eta=1.0,
    name=None,
):
    """RPN proposal generation (reference detection/generate_proposals_op.cc).
    Returns (rois [B, post_nms_top_n, 4] -1-padded with @LEN companion,
    roi_probs)."""
    helper = LayerHelper("generate_proposals", **locals())
    rois = helper.create_variable_for_type_inference("float32")
    probs = helper.create_variable_for_type_inference("float32")
    len_name = _new_len_var(helper, rois)
    helper.append_op(
        type="generate_proposals",
        inputs={
            "Scores": [scores.name],
            "BboxDeltas": [bbox_deltas.name],
            "ImInfo": [im_info.name],
            "Anchors": [anchors.name],
            "Variances": [variances.name],
        },
        outputs={
            "RpnRois": [rois.name],
            "RpnRoiProbs": [probs.name],
            "RoisLen": [len_name],
        },
        attrs={
            "pre_nms_topN": pre_nms_top_n,
            "post_nms_topN": post_nms_top_n,
            "nms_thresh": nms_thresh,
            "min_size": min_size,
        },
    )
    rois.stop_gradient = True
    probs.stop_gradient = True
    return rois, probs


def yolov3_loss(
    x,
    gtbox,
    gtlabel,
    anchors,
    class_num,
    ignore_thresh,
    loss_weight_xy=None,
    loss_weight_wh=None,
    loss_weight_conf_target=None,
    loss_weight_conf_notarget=None,
    loss_weight_class=None,
    name=None,
):
    """reference layers/detection.py yolov3_loss → yolov3_loss_op.h."""
    helper = LayerHelper("yolov3_loss", **locals())
    loss = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="yolov3_loss",
        inputs={
            "X": [x.name],
            "GTBox": [gtbox.name],
            "GTLabel": [gtlabel.name],
        },
        outputs={"Loss": [loss.name]},
        attrs={
            "anchors": list(anchors),
            "class_num": class_num,
            "ignore_thresh": ignore_thresh,
        },
    )
    return loss
