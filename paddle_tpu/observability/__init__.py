"""Always-on runtime telemetry (docs/observability.md).

The post-hoc profiler (paddle_tpu.profiler: RecordEvent tables, xla_trace)
answers "where did this session's time go"; this package answers "is the
run healthy RIGHT NOW" — the streaming complement a production jax_graft
deployment is operated with:

- registry:  typed thread-safe metrics (Counter/Gauge/Histogram) shared by
             every subsystem; resilience.health is a compat shim over it;
- stepstats: per-step StepStats collected from Executor/ParallelExecutor,
             the input pipeline's stall time, the NaN guard, and the
             pipeline-parallel schedule (runtime bubble fraction);
- export:    flag-gated JSONL event sink + Prometheus scrape file, per-host
             shards with a rank-0 merged view (FLAGS_telemetry_dir);
- opprof:    op-LEVEL attribution — per-op device-time/FLOPs profile
             (op_profile records, tools/op_profile.py), FLAGS_tensor_stats
             on-device output statistics, and FLAGS_nan_provenance
             first-bad-op localization when a NaN guard trips;
- tracing:   Dapper-style per-request distributed tracing across
             router -> replica -> batcher/scheduler -> engine, with tail
             sampling and per-process JSONL span shards (FLAGS_trace_dir);
- flightrec: dump-on-trigger anomaly bundles — recent spans + metrics +
             the triggering event, written atomically on a 5xx, breaker
             transition, NaN-guard trip, watchdog stall, staleness
             throttle or SLO alert (FLAGS_flightrec_dir);
- promparse: Prometheus exposition text -> registry-shaped snapshots,
             the exact inverse of registry.render_prometheus;
- aggregate: FleetAggregator — scrapes every replica's /metrics plus the
             router's registry, merges counters by sum / gauges per-replica
             / histograms bucket-wise (exact fleet p50/p99 on the shared
             grid), serves GET /fleet/metrics + /fleet/stats;
- slo:       declarative SLO objects + AlertEngine (SRE-workbook
             multi-window multi-burn-rate rules) + drift sentinels (EWMA
             latency drift, post-warmup retrace, goodput vs roofline).

Live view: `python tools/monitor.py <telemetry_dir>` (add `--watch N
--fleet_url <router>` for a refreshing fleet dashboard); traces render via
`python tools/trace_view.py <trace_dir>` and
`python tools/timeline.py --trace_path <trace_dir> --alerts_path <jsonl>`.
"""

from . import (  # noqa: F401
    aggregate,
    export,
    flightrec,
    opprof,
    promparse,
    registry,
    slo,
    stepstats,
    tracing,
)
from .aggregate import FleetAggregator
from .flightrec import FlightRecorder
from .slo import SLO, AlertEngine
from .tracing import NULL_SPAN, Span, Tracer
from .registry import Counter, Gauge, Histogram, MetricRegistry, default_registry
from .stepstats import (
    StepStats,
    StepStatsCollector,
    active,
    collector,
    maybe_flush,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "default_registry",
    "StepStats",
    "StepStatsCollector",
    "active",
    "collector",
    "maybe_flush",
    "registry",
    "stepstats",
    "export",
    "opprof",
    "tracing",
    "flightrec",
    "promparse",
    "aggregate",
    "slo",
    "NULL_SPAN",
    "Span",
    "Tracer",
    "FlightRecorder",
    "FleetAggregator",
    "SLO",
    "AlertEngine",
]
