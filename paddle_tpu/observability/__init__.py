"""Always-on runtime telemetry (docs/observability.md).

The post-hoc profiler (paddle_tpu.profiler: RecordEvent tables, xla_trace)
answers "where did this session's time go"; this package answers "is the
run healthy RIGHT NOW" — the streaming complement a production jax_graft
deployment is operated with:

- registry:  typed thread-safe metrics (Counter/Gauge/Histogram) shared by
             every subsystem; resilience.health is a compat shim over it;
- stepstats: per-step StepStats collected from Executor/ParallelExecutor,
             the input pipeline's stall time, the NaN guard, and the
             pipeline-parallel schedule (runtime bubble fraction);
- export:    flag-gated JSONL event sink + Prometheus scrape file, per-host
             shards with a rank-0 merged view (FLAGS_telemetry_dir);
- opprof:    op-LEVEL attribution — per-op device-time/FLOPs profile
             (op_profile records, tools/op_profile.py), FLAGS_tensor_stats
             on-device output statistics, and FLAGS_nan_provenance
             first-bad-op localization when a NaN guard trips;
- tracing:   Dapper-style per-request distributed tracing across
             router -> replica -> batcher/scheduler -> engine, with tail
             sampling and per-process JSONL span shards (FLAGS_trace_dir);
- flightrec: dump-on-trigger anomaly bundles — recent spans + metrics +
             the triggering event, written atomically on a 5xx, breaker
             transition, NaN-guard trip, watchdog stall or staleness
             throttle (FLAGS_flightrec_dir).

Live view: `python tools/monitor.py <telemetry_dir>`; traces render via
`python tools/trace_view.py <trace_dir>` and
`python tools/timeline.py --trace_path <trace_dir>`.
"""

from . import export, flightrec, opprof, registry, stepstats, tracing  # noqa: F401
from .flightrec import FlightRecorder
from .tracing import NULL_SPAN, Span, Tracer
from .registry import Counter, Gauge, Histogram, MetricRegistry, default_registry
from .stepstats import (
    StepStats,
    StepStatsCollector,
    active,
    collector,
    maybe_flush,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "default_registry",
    "StepStats",
    "StepStatsCollector",
    "active",
    "collector",
    "maybe_flush",
    "registry",
    "stepstats",
    "export",
    "opprof",
    "tracing",
    "flightrec",
    "NULL_SPAN",
    "Span",
    "Tracer",
    "FlightRecorder",
]
