"""Telemetry export: rotation-safe JSONL event sink + Prometheus scrape file.

Flag-gated (FLAGS_telemetry_dir, FLAGS_telemetry_interval_steps — flags.py):
when a dir is set, every recorded step appends one JSON line to a PER-HOST
shard file `telemetry-host<k>.jsonl` (k = jax.process_index(), 0 on a single
host), and every `interval_steps` steps a `snapshot` record (full metric
registry, health counters, device-memory watermarks, pipeline-bubble
estimate) plus a Prometheus text file `metrics-host<k>.prom` are written.

Schema (every record): {"kind": "step"|"snapshot", "step": int, "ts": float,
"host": int, ...}. Step records carry wall_ms/n_steps/feed_stall_ms/
cache_hit/nan_trip (+ pp/n_micro/schedule/loss when present); snapshot
records carry metrics/health/mem/bubble. tools/monitor.py renders the
stream; tools/timeline.py --telemetry_path turns it into chrome-trace
counter tracks.

Rotation: a shard that crosses `max_bytes` is renamed to `<name>.1`
(previous `.1` dropped) and a fresh shard is started — the sink is safe to
leave on for a multi-day run. Writes are line-buffered appends; the
Prometheus file and the merged view are written atomically (tmp + rename)
so a scraper never reads a torn file.

Multi-host: each process writes only its own shard (no cross-host writes to
contend on); process identity comes from the SAME jax.distributed rendezvous
parallel/multihost.init_distributed performs — after it, jax.process_index()
is the trainer rank. Rank 0 additionally maintains `telemetry-merged.jsonl`,
a ts-sorted merge of every host shard it can see (meaningful when the
telemetry dir is shared storage; per-host shards remain the ground truth).

Device-memory watermarks ride the snapshot records via
jax.local_devices()[*].memory_stats() — present on TPU, None on the CPU
test backend (the field is simply omitted there).
"""

import glob
import json
import os
import threading
import time

__all__ = [
    "TelemetryExporter",
    "device_memory_stats",
    "merge_host_shards",
    "SHARD_PATTERN",
]

SHARD_PATTERN = "telemetry-host*.jsonl*"
MERGED_NAME = "telemetry-merged.jsonl"


def _process_index():
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def _process_count():
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1


def device_memory_stats():
    """{mem_peak_bytes, mem_bytes_in_use, mem_limit_bytes} maxed/summed over
    local devices, or {} where the backend exposes no memory_stats (CPU)."""
    try:
        import jax

        peak = in_use = limit = 0
        seen = False
        for d in jax.local_devices():
            ms = d.memory_stats()
            if not ms:
                continue
            seen = True
            peak = max(peak, ms.get("peak_bytes_in_use", 0))
            in_use = max(in_use, ms.get("bytes_in_use", 0))
            limit = max(limit, ms.get("bytes_limit", 0))
        if not seen:
            return {}
        out = {"mem_peak_bytes": peak, "mem_bytes_in_use": in_use}
        if limit:
            out["mem_limit_bytes"] = limit
        return out
    except Exception:
        return {}


def _atomic_write(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def read_records(path):
    """Parse one JSONL file, skipping torn trailing lines (a crash mid-append
    leaves at most one)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def merge_host_shards(out_dir, out_name=MERGED_NAME):
    """ts-sorted merge of every host shard (rotated shards included) into
    `out_name`, written atomically. Returns the merged path (or None when no
    shards exist). Normally called by rank 0 at flush time; also usable
    post-hoc on a collected log dir."""
    paths = sorted(glob.glob(os.path.join(out_dir, SHARD_PATTERN)))
    paths = [p for p in paths if not p.endswith(".tmp")]
    if not paths:
        return None
    records = []
    for p in paths:
        records.extend(read_records(p))
    records.sort(key=lambda r: (r.get("ts", 0), r.get("host", 0)))
    out = os.path.join(out_dir, out_name)
    _atomic_write(out, "".join(json.dumps(r) + "\n" for r in records))
    return out


class TelemetryExporter:
    def __init__(self, out_dir, interval_steps=50, max_bytes=64 << 20,
                 registry=None):
        from . import registry as _registry

        self.out_dir = out_dir
        self.interval_steps = max(int(interval_steps), 1)
        self.max_bytes = max_bytes
        self.registry = registry or _registry.default_registry()
        self.host = _process_index()
        os.makedirs(out_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._shard_path = os.path.join(
            out_dir, "telemetry-host%d.jsonl" % self.host
        )
        self._prom_path = os.path.join(
            out_dir, "metrics-host%d.prom" % self.host
        )
        self._fh = open(self._shard_path, "a")
        self._steps_since_flush = 0

    # ---- sink -----------------------------------------------------------
    def _write(self, record):
        record.setdefault("ts", time.time())
        record["host"] = self.host
        line = json.dumps(record) + "\n"
        with self._lock:
            self._fh.write(line)
            self._fh.flush()
            if self._fh.tell() >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self):
        self._fh.close()
        os.replace(self._shard_path, self._shard_path + ".1")
        self._fh = open(self._shard_path, "a")

    def write_record(self, record):
        """Append one arbitrary (non-step) record to this host's shard —
        the sink for op_profile / tensor_stats / nan_provenance records
        (observability/opprof.py). The record must carry a "kind"; ts/host
        are stamped like every other line."""
        if not record.get("kind"):
            raise ValueError("telemetry record needs a 'kind': %r" % (record,))
        self._write(dict(record))

    def on_step(self, step_record, collector=None):
        self._write(step_record)
        self._steps_since_flush += step_record.get("n_steps", 1)
        if self._steps_since_flush >= self.interval_steps:
            self.flush(collector)

    def flush(self, collector=None):
        """Interval work: snapshot record into the shard, Prometheus scrape
        file, rank-0 merged view."""
        self._steps_since_flush = 0
        from ..resilience import health as _health

        snap = {
            "kind": "snapshot",
            "step": getattr(collector, "_step", None) if collector else None,
            "metrics": self.registry.snapshot(),
            "health": _health.snapshot(),
        }
        mem = device_memory_stats()
        if mem:
            snap["mem"] = mem
            self.registry.gauge(
                "device/mem_peak_bytes",
                "max over local devices of peak_bytes_in_use",
            ).set(mem["mem_peak_bytes"])
        if collector is not None:
            bub = collector.bubble_estimate()
            if bub is not None:
                snap["bubble"] = bub
        self._write(snap)
        _atomic_write(self._prom_path, self.registry.to_prometheus())
        if self.host == 0 and _process_count() > 1:
            try:
                merge_host_shards(self.out_dir)
            except OSError:
                pass  # shared-fs hiccup: shards remain the ground truth

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
