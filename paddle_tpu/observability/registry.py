"""Typed, thread-safe metric registry: Counter / Gauge / Histogram.

Reference analog: the reference's observability is strictly post-hoc
(platform/profiler RecordEvent tables read AFTER a session stops); a
production runtime also needs the streaming complement — always-on named
metrics an operator can scrape mid-run, the way TPU fleets pair xprof traces
with continuous monitoring. This registry is that surface: every subsystem
(executor step stats, input pipeline, resilience health counters) registers
typed metrics here, and observability/export.py serializes `snapshot()` into
JSONL / Prometheus text.

Design constraints:
- one lock per registry (metrics are updated on hot paths, but a training
  step is milliseconds — an uncontended lock acquire is ~100 ns);
- histograms have BOUNDED buckets (fixed upper-bound list), so memory is
  O(metrics), never O(steps);
- labels are kwargs on counters/gauges (`inc(1, kind="rpc")`), stored per
  label-tuple; histograms are label-free by design (bounded cardinality);
- re-registering a name returns the existing metric, and a kind mismatch is
  a hard error (two subsystems silently sharing "steps" as counter AND
  gauge is a bug, not a merge).

`resilience.health` is a compatibility shim over counters named
"health/<name>" — its incr/get/snapshot/reset API is unchanged, but the
counters now ride the same export path as everything else.
"""

import math
import re
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "default_registry",
    "render_prometheus",
    "DEFAULT_MS_BUCKETS",
]

# default buckets for millisecond-scale latencies: ~exponential, 0.1 ms ..
# 2 min, 23 buckets + overflow — per-step wall times from a CPU unit test
# (~1 ms) to a multi-minute pathological stall all land in a bounded table
DEFAULT_MS_BUCKETS = (
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 25000, 50000, 120000,
)


def _label_key(labels):
    return tuple(sorted(labels.items()))


class _Metric:
    kind = None

    def __init__(self, name, help, lock):
        self.name = name
        self.help = help
        self._lock = lock


class Counter(_Metric):
    """Monotonic float counter, optionally labelled."""

    kind = "counter"

    def __init__(self, name, help, lock):
        super().__init__(name, help, lock)
        self._values = {}

    def inc(self, n=1, **labels):
        if n < 0:
            raise ValueError("counter %r cannot decrease (n=%r)" % (self.name, n))
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n
            return self._values[key]

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def clear(self):
        with self._lock:
            self._values.clear()

    def _snapshot_locked(self):
        return {
            "kind": self.kind,
            "values": {_render_labels(k): v for k, v in self._values.items()},
        }


class Gauge(_Metric):
    """Last-written value, optionally labelled."""

    kind = "gauge"

    def __init__(self, name, help, lock):
        super().__init__(name, help, lock)
        self._values = {}

    def set(self, value, **labels):
        with self._lock:
            self._values[_label_key(labels)] = value
        return value

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels))

    def clear(self):
        with self._lock:
            self._values.clear()

    def _snapshot_locked(self):
        return {
            "kind": self.kind,
            "values": {_render_labels(k): v for k, v in self._values.items()},
        }


class Histogram(_Metric):
    """Fixed-bucket histogram: counts per upper bound + one overflow bucket,
    running sum/count/min/max. Quantiles are estimated by linear
    interpolation inside the containing bucket — exact enough for p50/p95
    dashboards, O(buckets) memory forever."""

    kind = "histogram"

    def __init__(self, name, help, lock, buckets=DEFAULT_MS_BUCKETS):
        super().__init__(name, help, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram %r needs at least one bucket" % name)
        self._counts = [0] * (len(self.buckets) + 1)  # +1: overflow
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value):
        value = float(value)
        with self._lock:
            i = 0
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self):
        with self._lock:
            return self._count

    def percentile(self, q):
        """q in [0, 100]. Interpolated within the containing bucket; the
        overflow bucket reports the observed max."""
        with self._lock:
            if not self._count:
                return None
            target = self._count * q / 100.0
            cum = 0
            lo = 0.0
            for i, ub in enumerate(self.buckets):
                prev = cum
                cum += self._counts[i]
                if cum >= target:
                    frac = (target - prev) / max(self._counts[i], 1)
                    return min(lo + frac * (ub - lo), self._max)
                lo = ub
            return self._max

    def clear(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = math.inf
            self._max = -math.inf

    def _snapshot_locked(self):
        return {
            "kind": self.kind,
            "buckets": list(self.buckets),
            "counts": list(self._counts),
            "sum": self._sum,
            "count": self._count,
            "min": self._min if self._count else None,
            "max": self._max if self._count else None,
        }


def _render_labels(key):
    """label tuple -> stable string form for snapshots ('' when unlabelled)."""
    return ",".join("%s=%s" % (k, v) for k, v in key)


def _label_pairs(labels):
    """Rendered label string -> [[k, v], ...]. A piece without '=' is the
    tail of a comma-holding label VALUE split apart by the join — rejoin it
    instead of 500ing every /metrics scrape."""
    pairs = []
    for p in labels.split(","):
        if "=" in p:
            pairs.append(p.split("=", 1))
        elif pairs:
            pairs[-1][1] += "," + p
    return pairs


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name):
    n = _PROM_BAD.sub("_", name)
    return ("_" + n) if n[:1].isdigit() else n


def _escape_label(v):
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_value(v):
    """Full-precision sample value: ints stay integral, floats render via
    repr (shortest round-trip form), non-finite uses the Prometheus
    spellings. %g would drop digits and break promparse exactness."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(v)


def render_prometheus(snapshot, helps=None):
    """Registry-shaped snapshot dict -> Prometheus text exposition (0.0.4).

    Spec-conformant for real scrapers — `# HELP`/`# TYPE` lines, cumulative
    `le`-labelled `_bucket` series ending in `+Inf`, `_sum`/`_count` per
    histogram, escaped label values — plus two LOSSLESS extras that ride as
    legal comment / untyped-sample lines so observability.promparse can
    invert the text exactly back into the snapshot:

      - ``# NAME <prom_name> <registry_name>`` maps each sanitized sample
        family back to its registry name (slashes survive the round trip);
      - ``<name>_min`` / ``<name>_max`` samples carry the histogram extremes
        the standard exposition drops (percentile() clamps against max, and
        the fleet aggregator needs them for exact merged percentiles).

    Used by MetricRegistry.to_prometheus and by aggregate.FleetAggregator
    for the merged `GET /fleet/metrics` view.
    """
    helps = helps or {}
    lines = []
    for name, rec in sorted(snapshot.items()):
        pname = _prom_name(name)
        if helps.get(name):
            lines.append("# HELP %s %s" % (
                pname,
                str(helps[name]).replace("\\", "\\\\").replace("\n", "\\n"),
            ))
        lines.append("# TYPE %s %s" % (pname, rec["kind"]))
        lines.append("# NAME %s %s" % (pname, name))
        if rec["kind"] in ("counter", "gauge"):
            for labels, v in sorted(rec["values"].items()):
                if labels:
                    rendered = ",".join(
                        '%s="%s"' % (k, _escape_label(val))
                        for k, val in _label_pairs(labels)
                    )
                    lines.append("%s{%s} %s" % (pname, rendered, _fmt_value(v)))
                else:
                    lines.append("%s %s" % (pname, _fmt_value(v)))
        else:  # histogram
            cum = 0
            for ub, c in zip(rec["buckets"], rec["counts"]):
                cum += c
                lines.append(
                    '%s_bucket{le="%s"} %d' % (pname, _fmt_value(float(ub)), cum)
                )
            cum += rec["counts"][-1]
            lines.append('%s_bucket{le="+Inf"} %d' % (pname, cum))
            lines.append("%s_sum %s" % (pname, _fmt_value(rec["sum"])))
            lines.append("%s_count %d" % (pname, rec["count"]))
            if rec.get("min") is not None:
                lines.append("%s_min %s" % (pname, _fmt_value(rec["min"])))
            if rec.get("max") is not None:
                lines.append("%s_max %s" % (pname, _fmt_value(rec["max"])))
    return "\n".join(lines) + "\n"


class MetricRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        "metric %r already registered as %s, wanted %s"
                        % (name, m.kind, cls.kind)
                    )
                return m
            m = cls(name, help, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_MS_BUCKETS):
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name):
        """Existing metric or None — lookups must not create (health.get's
        contract: reading an unknown counter is 0, not a registration)."""
        with self._lock:
            return self._metrics.get(name)

    def names(self, prefix=""):
        with self._lock:
            return sorted(n for n in self._metrics if n.startswith(prefix))

    def remove(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self, prefix=""):
        """Clear values (and, with a prefix, the registrations themselves) —
        health.reset semantics: a reset counter disappears from snapshots."""
        with self._lock:
            for name in list(self._metrics):
                if name.startswith(prefix):
                    del self._metrics[name]

    def snapshot(self):
        """{name: {kind, values|buckets...}} — one lock pass, so the view is
        consistent across metrics."""
        with self._lock:
            return {
                name: m._snapshot_locked()
                for name, m in sorted(self._metrics.items())
            }

    def to_prometheus(self):
        """Prometheus text exposition of the whole registry (export.py writes
        this to the flag-gated scrape file; promparse.parse inverts it
        exactly — see render_prometheus)."""
        snap = self.snapshot()
        with self._lock:
            helps = {n: m.help for n, m in self._metrics.items()}
        return render_prometheus(snap, helps=helps)


_default = MetricRegistry()


def default_registry():
    return _default
