"""Parse Prometheus text exposition back into registry-shaped snapshots.

The fleet aggregator (observability/aggregate.py) scrapes every replica's
`GET /metrics` and needs the leaf data back in the exact shape
MetricRegistry.snapshot() produces, so counters can be merged by sum and
histograms bucket-wise. registry.render_prometheus emits two lossless
extras on top of the standard 0.0.4 exposition — a ``# NAME`` comment
mapping the sanitized sample name back to the registry name, and
``_min``/``_max`` samples per histogram — which makes the inversion exact:

    parse(registry.to_prometheus()) == registry.snapshot()

holds bit-for-bit (tested in tests/test_slo.py). Text from a foreign
exporter still parses: missing NAME comments fall back to the sample name,
untyped samples are treated as gauges, and histograms without min/max get
``None`` extremes (percentile clamping then degrades gracefully).
"""

import math
import re

__all__ = ["parse", "parse_labels"]

# name{labels} value [timestamp] — timestamp tolerated and dropped
_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(.*)\})?"
    r"\s+(\S+)"
    r"(?:\s+-?\d+)?$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_HIST_SUFFIXES = ("_bucket", "_sum", "_count", "_min", "_max")


def _unescape(s):
    if "\\" not in s:
        return s
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            n = s[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(n, "\\" + n))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _num(tok):
    if tok in ("+Inf", "Inf"):
        return math.inf
    if tok == "-Inf":
        return -math.inf
    if tok == "NaN":
        return math.nan
    try:
        return int(tok)
    except ValueError:
        return float(tok)


def parse_labels(text):
    """Label body (the part between { and }) -> dict of unescaped values."""
    return {m.group(1): _unescape(m.group(2)) for m in _LABEL.finditer(text)}


def _label_key(labels):
    """Label dict -> the snapshot's rendered form ('' when unlabelled),
    matching registry._render_labels over sorted items."""
    return ",".join("%s=%s" % (k, v) for k, v in sorted(labels.items()))


def _family(pname, hist_names):
    """Histogram family for a sample name, or None. `step_ms_bucket` folds
    into `step_ms` only when step_ms is TYPEd as a histogram, so a real
    gauge that merely ends in _sum is left alone."""
    for suffix in _HIST_SUFFIXES:
        if pname.endswith(suffix):
            base = pname[: -len(suffix)]
            if base in hist_names:
                return base, suffix
    return None


def parse(text):
    """Exposition text -> {name: {kind, values | buckets/counts/sum/...}}.

    Unknown comment lines are skipped per spec; torn/garbage sample lines
    are skipped rather than raised (a replica dying mid-write must not take
    the whole fleet scrape down with it).
    """
    types = {}   # prom name -> kind
    names = {}   # prom name -> original registry name
    samples = []  # (prom name, label dict, value) in document order
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            elif len(parts) >= 4 and parts[1] == "NAME":
                names[parts[2]] = parts[3]
            continue  # HELP and arbitrary comments
        m = _SAMPLE.match(line)
        if not m:
            continue
        pname, labelstr, valtok = m.groups()
        try:
            value = _num(valtok)
        except ValueError:
            continue
        samples.append((pname, parse_labels(labelstr) if labelstr else {},
                        value))

    hist_names = {p for p, t in types.items() if t == "histogram"}
    out = {}
    hists = {}  # prom name -> accumulator
    for pname, labels, value in samples:
        fam = _family(pname, hist_names)
        if fam is not None:
            base, suffix = fam
            acc = hists.setdefault(
                base,
                {"le": [], "sum": 0.0, "count": 0, "min": None, "max": None},
            )
            if suffix == "_bucket":
                le = labels.get("le")
                if le is not None:
                    acc["le"].append((_num(le), value))
            elif suffix == "_sum":
                acc["sum"] = value
            elif suffix == "_count":
                acc["count"] = value
            elif suffix == "_min":
                acc["min"] = value
            else:
                acc["max"] = value
            continue
        kind = types.get(pname, "gauge")
        if kind not in ("counter", "gauge"):
            kind = "gauge"  # untyped / summary samples degrade to gauges
        name = names.get(pname, pname)
        rec = out.setdefault(name, {"kind": kind, "values": {}})
        if rec["kind"] == kind:
            rec["values"][_label_key(labels)] = value

    for pname, acc in hists.items():
        pairs = sorted(acc["le"], key=lambda p: p[0])
        bounds = [le for le, _ in pairs if not math.isinf(le)]
        cums = [c for le, c in pairs if not math.isinf(le)]
        counts = [
            c - (cums[i - 1] if i else 0) for i, c in enumerate(cums)
        ]
        inf_cum = next(
            (c for le, c in pairs if math.isinf(le) and le > 0), None
        )
        overflow = (inf_cum - cums[-1]) if (inf_cum is not None and cums) \
            else (inf_cum or 0)
        counts.append(overflow)
        out[names.get(pname, pname)] = {
            "kind": "histogram",
            "buckets": [float(b) for b in bounds],
            "counts": counts,
            "sum": acc["sum"],
            "count": acc["count"],
            "min": acc["min"],
            "max": acc["max"],
        }
    return dict(sorted(out.items()))
