"""Anomaly flight recorder: dump-on-trigger black box for the serving fleet.

Aggregate metrics say a breaker opened; the flight recorder preserves what
the process looked like the INSTANT it happened. It rides the tracing
module's bounded span ring (observability/tracing.py — the lookback window
of recently ended spans, sampled or not) and, when a trigger fires, writes
one atomic bundle directory under FLAGS_flightrec_dir:

    bundle-<ms>-<reason>-p<pid>/
      event.json    the triggering event: reason, ts, pid/host, caller info
      spans.jsonl   the span ring at trigger time (the request-level story)
      metrics.json  full registry snapshot + health counters + the health
                    deltas since the previous trigger (what moved)
      env.json      flags, FLAGS_*/PADDLE_TPU_*/JAX_* environment, argv

Bundles are staged in a ``.tmp-`` directory and os.rename()d into place, so
a collector never sees a torn bundle; at most FLAGS_flightrec_max_bundles
are kept (oldest pruned) and triggers for one reason are rate-limited to
one per FLAGS_flightrec_min_interval_s.

Trigger sites (each passes reason-specific context):
- ``http_5xx``            a replica answered 5xx (serving/server.py)
- ``router_5xx``          the router surfaced a 5xx to a client
- ``breaker_transition``  a circuit breaker changed state (fleet/router.py)
- ``nan_guard``           the resilience NaN guard skipped a step (executor)
- ``watchdog_stall``      a supervised step blew its deadline (resilience/
                          elastic.py)
- ``staleness_throttle``  the online trainer refused to publish because the
                          fleet lagged too far behind (online/trainer.py)
- ``slo_alert``           an SLO burn-rate alert or drift sentinel fired
                          (observability/slo.py) — info carries the
                          offending window's merged series

The module-level ``trigger(reason, **info)`` is the only call sites use; it
is a near-free no-op when FLAGS_flightrec_dir is unset and must NEVER raise
into the path that tripped it.
"""

import json
import os
import shutil
import sys
import threading
import time

__all__ = ["FlightRecorder", "recorder", "trigger", "reset"]

BUNDLE_PREFIX = "bundle-"


class FlightRecorder:
    def __init__(self, out_dir, max_bundles=16, min_interval_s=2.0):
        from . import registry as _registry
        from .export import _process_index

        self.out_dir = out_dir
        self.max_bundles = max(int(max_bundles), 1)
        self.min_interval_s = float(min_interval_s)
        self._host = _process_index()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._last = {}  # reason -> monotonic time of last bundle
        self._prev_health = None
        reg = _registry.default_registry()
        self._m_bundles = reg.counter(
            "flightrec/bundles", "flight-recorder bundles written, by reason"
        )
        self._m_suppressed = reg.counter(
            "flightrec/suppressed", "triggers dropped by the rate limit"
        )
        os.makedirs(out_dir, exist_ok=True)

    def trigger(self, reason, **info):
        """Write one bundle for `reason` (rate-limited per reason). Returns
        the bundle path, or None when suppressed/disabled. Never raises —
        the recorder must not take down the path that tripped it."""
        try:
            now = time.monotonic()
            with self._lock:
                last = self._last.get(reason)
                if last is not None and now - last < self.min_interval_s:
                    self._m_suppressed.inc(reason=reason)
                    return None
                self._last[reason] = now
            path = self._write_bundle(reason, info)
            self._m_bundles.inc(reason=reason)
            return path
        except Exception:
            return None

    # ---- bundle assembly --------------------------------------------------
    def _write_bundle(self, reason, info):
        from . import tracing as _tracing

        name = "%s%013d-%s-p%d" % (
            BUNDLE_PREFIX, int(time.time() * 1e3), reason, self._pid
        )
        tmp = os.path.join(self.out_dir, ".tmp-" + name)
        os.makedirs(tmp)
        event = {
            "reason": reason,
            "ts": time.time(),
            "pid": self._pid,
            "host": self._host,
            "info": _jsonable(info),
        }
        self._dump(tmp, "event.json", event)
        spans = _tracing.tracer().recent()
        with open(os.path.join(tmp, "spans.jsonl"), "w") as f:
            for rec in spans:
                f.write(json.dumps(rec) + "\n")
        self._dump(tmp, "metrics.json", self._metrics())
        self._dump(tmp, "env.json", {
            "flags": _flags_snapshot(),
            "env": {
                k: v for k, v in os.environ.items()
                if k.startswith(("FLAGS_", "PADDLE_TPU_", "JAX_"))
            },
            "argv": list(sys.argv),
        })
        final = os.path.join(self.out_dir, name)
        os.rename(tmp, final)  # atomic publish: no torn bundles
        self._prune()
        return final

    def _metrics(self):
        from . import registry as _registry

        out = {"metrics": {}, "health": {}, "health_delta": {}}
        try:
            out["metrics"] = _registry.default_registry().snapshot()
        except Exception:
            pass
        try:
            from ..resilience import health as _health

            cur = dict(_health.snapshot())
            out["health"] = cur
            prev = self._prev_health or {}
            out["health_delta"] = {
                k: v - prev.get(k, 0)
                for k, v in cur.items()
                if v - prev.get(k, 0)
            }
            self._prev_health = cur
        except Exception:
            pass
        return out

    @staticmethod
    def _dump(dirname, fname, obj):
        with open(os.path.join(dirname, fname), "w") as f:
            json.dump(obj, f, indent=1, default=repr)

    def _prune(self):
        bundles = sorted(
            d for d in os.listdir(self.out_dir)
            if d.startswith(BUNDLE_PREFIX)
        )
        for stale in bundles[:-self.max_bundles]:
            shutil.rmtree(os.path.join(self.out_dir, stale),
                          ignore_errors=True)

    def bundles(self):
        """Bundle paths, oldest first."""
        return [
            os.path.join(self.out_dir, d)
            for d in sorted(os.listdir(self.out_dir))
            if d.startswith(BUNDLE_PREFIX)
        ]


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


def _flags_snapshot():
    try:
        from .. import flags as _flags

        return _flags.get_flags()
    except Exception:
        return {}


# ---- process singleton ----------------------------------------------------
_rec = None
_disabled = False  # cached "flags say off": trigger() stays near-free
_rec_lock = threading.Lock()


def recorder():
    """The process recorder built from FLAGS_flightrec_* on first use, or
    None when FLAGS_flightrec_dir is unset."""
    global _rec, _disabled
    if _rec is not None or _disabled:
        return _rec
    with _rec_lock:
        if _rec is None and not _disabled:
            from .. import flags as _flags

            f = _flags.get_flags([
                "flightrec_dir", "flightrec_max_bundles",
                "flightrec_min_interval_s",
            ])
            if f["flightrec_dir"]:
                _rec = FlightRecorder(
                    f["flightrec_dir"],
                    max_bundles=f["flightrec_max_bundles"],
                    min_interval_s=f["flightrec_min_interval_s"],
                )
            else:
                _disabled = True
    return _rec


def trigger(reason, **info):
    """Fire one trigger. No-op (returns None) when the recorder is off."""
    rec = _rec
    if rec is None:
        if _disabled:
            return None
        rec = recorder()
        if rec is None:
            return None
    return rec.trigger(reason, **info)


def reset():
    """Forget the process recorder so the next call re-reads flags."""
    global _rec, _disabled
    with _rec_lock:
        _rec, _disabled = None, False
