"""Fleet-wide metrics aggregation: one pane of glass over N replicas.

Monarch-style (VLDB'20) leaf scraping: a `FleetAggregator` periodically
pulls every registered replica's `GET /metrics` exposition, parses it back
into registry-shaped snapshots (observability/promparse.py), folds in the
router's own registry, and merges:

- counters   by SUM across sources, per label set;
- gauges     into per-replica-labelled series (a `replica=<name>` label is
             added), with min/max/sum/mean rollups computed in `stats()`;
- histograms BUCKET-WISE — every process shares the registry's bounded
             bucket grid (DEFAULT_MS_BUCKETS unless a metric opts out), so
             element-wise count addition yields exactly the histogram a
             single pooled process would have held, and fleet-level
             p50/p99 from `hist_percentile` are bit-equal to percentiles
             over the pooled raw observations (tested + gated by
             `bench.py slo`). Mismatched grids are skipped and counted.

The merged view is served by the fleet router as `GET /fleet/metrics`
(exposition text via registry.render_prometheus) and `GET /fleet/stats`
(JSON rollups), rendered live by `tools/monitor.py --fleet_url`, and
retained as a bounded in-memory history of (ts, snapshot) pairs — the
window store the SLO burn-rate engine (observability/slo.py) evaluates
over. A replica dying mid-scrape is tolerated: its fetch error is recorded
in the scrape metadata and `fleet/scrape_errors`, and the merge proceeds
with the survivors.

Everything here is pull-based and off by default: no scrape loop runs
unless Router(fleet_metrics=True) or FleetAggregator.start() is called.
"""

import json
import threading
import time
import urllib.request
from collections import deque

from . import promparse
from . import registry as _registry

__all__ = [
    "FleetAggregator",
    "FleetSnapshot",
    "hist_percentile",
    "merge_snapshots",
]


def hist_percentile(rec, q):
    """Percentile of a snapshot-shaped histogram record — the same linear
    interpolation Histogram.percentile performs, operating on merged
    counts. Identical arithmetic on identical counts/max is what makes the
    fleet p99 bit-equal to the pooled-observation p99."""
    count = rec.get("count") or 0
    if not count:
        return None
    target = count * q / 100.0
    cum = 0
    lo = 0.0
    counts = rec["counts"]
    mx = rec.get("max")
    for i, ub in enumerate(rec["buckets"]):
        prev = cum
        cum += counts[i]
        if cum >= target:
            frac = (target - prev) / max(counts[i], 1)
            v = lo + frac * (ub - lo)
            return min(v, mx) if mx is not None else v
        lo = ub
    return mx if mx is not None else rec["buckets"][-1]


def _labels_with(labels, **extra):
    """Add labels to a rendered label string, keeping the sorted form the
    registry snapshot uses."""
    pairs = [tuple(p) for p in _registry._label_pairs(labels)] if labels else []
    pairs.extend((k, str(v)) for k, v in extra.items())
    return ",".join("%s=%s" % (k, v) for k, v in sorted(pairs))


def _merge_minmax(a, b, fn):
    if a is None:
        return b
    if b is None:
        return a
    return fn(a, b)


def merge_snapshots(named, mismatch_counter=None):
    """[(source_name, snapshot)] -> one merged registry-shaped snapshot.

    Sources whose histogram bucket grid disagrees with the first-seen grid
    for a metric are skipped for that metric (and counted on
    `mismatch_counter` when given) — summing counts across different
    grids would silently corrupt percentiles."""
    merged = {}
    for src, snap in named:
        for name, rec in snap.items():
            kind = rec.get("kind")
            dst = merged.get(name)
            if dst is not None and dst.get("kind") != kind:
                if mismatch_counter is not None:
                    mismatch_counter.inc(metric=name)
                continue
            if kind == "counter":
                if dst is None:
                    dst = merged[name] = {"kind": "counter", "values": {}}
                for labels, v in rec.get("values", {}).items():
                    dst["values"][labels] = dst["values"].get(labels, 0) + v
            elif kind == "gauge":
                if dst is None:
                    dst = merged[name] = {"kind": "gauge", "values": {}}
                for labels, v in rec.get("values", {}).items():
                    dst["values"][_labels_with(labels, replica=src)] = v
            elif kind == "histogram":
                if dst is None:
                    merged[name] = {
                        "kind": "histogram",
                        "buckets": list(rec["buckets"]),
                        "counts": list(rec["counts"]),
                        "sum": rec["sum"],
                        "count": rec["count"],
                        "min": rec.get("min"),
                        "max": rec.get("max"),
                    }
                else:
                    if list(dst["buckets"]) != list(rec["buckets"]):
                        if mismatch_counter is not None:
                            mismatch_counter.inc(metric=name)
                        continue
                    dst["counts"] = [
                        a + b for a, b in zip(dst["counts"], rec["counts"])
                    ]
                    dst["sum"] += rec["sum"]
                    dst["count"] += rec["count"]
                    dst["min"] = _merge_minmax(dst["min"], rec.get("min"), min)
                    dst["max"] = _merge_minmax(dst["max"], rec.get("max"), max)
    return dict(sorted(merged.items()))


class FleetSnapshot:
    """One scrape round: wall time, the merged snapshot, per-target meta."""

    __slots__ = ("ts", "merged", "targets")

    def __init__(self, ts, merged, targets):
        self.ts = ts
        self.merged = merged
        self.targets = targets


def _default_fetch(url, timeout_s):
    with urllib.request.urlopen(url + "/metrics", timeout=timeout_s) as r:
        return r.read().decode("utf-8", "replace")


class FleetAggregator:
    """Scrape loop + bounded snapshot history (see module docstring).

    `targets` is {name: base_url} or a callable returning one — the router
    passes a closure over its replica table so membership changes are
    picked up on the next scrape. `fetch` and `clock` are injectable for
    tests."""

    def __init__(self, targets, local_registry=None, local_name="router",
                 interval_s=2.0, timeout_s=2.0, history_s=6 * 3600 + 600,
                 max_history=4096, clock=time.time, fetch=None):
        self._targets = targets
        self._local_registry = local_registry
        self._local_name = local_name
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.history_s = float(history_s)
        self._clock = clock
        self._fetch = fetch or _default_fetch
        self._lock = threading.Lock()
        self._history = deque(maxlen=max_history)
        self._listeners = []
        self._stop = threading.Event()
        self._thread = None
        reg = local_registry or _registry.default_registry()
        self._m_scrapes = reg.counter(
            "fleet/scrapes", "aggregator scrape rounds completed"
        )
        self._m_errors = reg.counter(
            "fleet/scrape_errors", "replica /metrics fetches that failed"
        )
        self._m_mismatch = reg.counter(
            "fleet/scrape_grid_mismatch",
            "histogram merges skipped for a disagreeing bucket grid",
        )
        self._h_scrape = reg.histogram(
            "fleet/scrape_ms", "wall time of one full scrape+merge round"
        )

    # ---- scraping ---------------------------------------------------------
    def add_listener(self, cb):
        """cb(FleetSnapshot) after every scrape — the AlertEngine hook."""
        self._listeners.append(cb)

    def scrape_once(self):
        t0 = time.perf_counter()
        now = self._clock()
        named = []
        meta = {}
        if self._local_registry is not None:
            named.append((self._local_name, self._local_registry.snapshot()))
            meta[self._local_name] = {"ok": True, "error": None,
                                      "scrape_ms": 0.0}
        targets = (self._targets() if callable(self._targets)
                   else self._targets)
        for name, url in sorted(dict(targets).items()):
            f0 = time.perf_counter()
            try:
                snap = promparse.parse(self._fetch(url, self.timeout_s))
                named.append((name, snap))
                meta[name] = {
                    "ok": True, "error": None,
                    "scrape_ms": round((time.perf_counter() - f0) * 1e3, 3),
                }
            except Exception as e:  # dead mid-scrape: merge the survivors
                self._m_errors.inc(replica=name)
                meta[name] = {
                    "ok": False, "error": repr(e),
                    "scrape_ms": round((time.perf_counter() - f0) * 1e3, 3),
                }
        merged = merge_snapshots(named, mismatch_counter=self._m_mismatch)
        fs = FleetSnapshot(now, merged, meta)
        with self._lock:
            self._history.append(fs)
            while (len(self._history) > 1
                   and now - self._history[0].ts > self.history_s):
                self._history.popleft()
        self._m_scrapes.inc()
        self._h_scrape.observe((time.perf_counter() - t0) * 1e3)
        for cb in list(self._listeners):
            cb(fs)
        return fs

    def latest(self):
        with self._lock:
            return self._history[-1] if self._history else None

    def history(self, window_s=None):
        """Ascending [(ts, merged_snapshot)] — the SLO engine's window
        store."""
        with self._lock:
            items = list(self._history)
        if window_s is not None and items:
            cutoff = items[-1].ts - window_s
            items = [fs for fs in items if fs.ts >= cutoff]
        return [(fs.ts, fs.merged) for fs in items]

    # ---- serving-side views ----------------------------------------------
    def metrics_text(self):
        """Merged fleet snapshot as exposition text (GET /fleet/metrics)."""
        fs = self.latest() or self.scrape_once()
        return _registry.render_prometheus(fs.merged)

    def stats(self):
        """JSON-shaped fleet rollup (GET /fleet/stats)."""
        fs = self.latest() or self.scrape_once()
        counters, gauges, hists = {}, {}, {}
        for name, rec in fs.merged.items():
            if rec["kind"] == "counter":
                vals = [v for v in rec["values"].values()
                        if isinstance(v, (int, float))]
                counters[name] = {"total": sum(vals), "series": len(vals)}
            elif rec["kind"] == "gauge":
                vals = [v for v in rec["values"].values()
                        if isinstance(v, (int, float))]
                if vals:
                    gauges[name] = {
                        "n": len(vals),
                        "min": min(vals),
                        "max": max(vals),
                        "sum": sum(vals),
                        "mean": sum(vals) / len(vals),
                    }
            else:
                hists[name] = {
                    "count": rec["count"],
                    "sum": rec["sum"],
                    "min": rec.get("min"),
                    "max": rec.get("max"),
                    "p50": hist_percentile(rec, 50),
                    "p90": hist_percentile(rec, 90),
                    "p99": hist_percentile(rec, 99),
                }
        return {
            "ts": fs.ts,
            "interval_s": self.interval_s,
            "targets": fs.targets,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }

    def stats_json(self):
        return json.dumps(self.stats())

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-aggregator", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # a bad round must not kill the loop
                pass
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)
