"""Op-level attribution: the layer BELOW step telemetry (docs/observability.md).

PR 4's StepStats answers "how long was the step"; this module answers the
three questions one level down, each a leg of the same subsystem:

1. COST ATTRIBUTION — fold the profiler's per-HLO device timings (xplane
   events, profiler.device_instr_events) and XLA cost-analysis stats back
   onto fluid op INSTANCES via the nested named_scope metadata
   registry.lower_ops emits ('.../<type>/out=<first output>/...'), into a
   per-op table (count, total/mean device ms, FLOPs, % of step) exported as
   an "op_profile" telemetry record and rendered by tools/op_profile.py.
   On backends without xplane ProfileData (the CPU test backend), the
   FLAGS_profile_ops eager tables provide the same rows from host events.

2. TENSOR-STATS INSTRUMENTATION — FLAGS_tensor_stats=<glob> selects ops
   whose outputs get mean/std/absmax/nonfinite-count computed ON DEVICE
   inside the compiled step (executor._CompiledBlock stacks them into one
   [n,4] array riding the existing created-persistables output — ONE host
   sync per run, the same trick as the nan-guard reduce), streamed as
   "tensor_stats" records + per-op registry gauges.

3. NAN PROVENANCE — when the resilience NaN guard or FLAGS_check_nan_inf
   trips and FLAGS_nan_provenance is set, the step's saved feed is replayed
   through an op-by-op interpreter walk (localize_nonfinite) that stops at
   the FIRST op emitting non-finite output and writes a provenance record
   (op type/name, input stats, attrs, step index) plus a
   health/nan_provenance counter. The reference's FLAGS_check_nan_inf threw
   AT the offending op because it interpreted op-by-op; a whole-block XLA
   computation has no such op boundary, so provenance is recovered by
   re-execution instead.

Everything here is off by default; the only hot-path cost when disabled is
the flags lookup the executor already pays (acceptance bound shared with
PR 4's telemetry).

Reference analog: operator.cc per-op RecordEvent tables + the op-level
FLAGS_check_nan_inf raise site (operator.cc:778), and device_tracer.cc's
kernel->op correlation.
"""

import fnmatch
import math
import sys
import threading

__all__ = [
    "TENSOR_STATS_KEY",
    "STAT_FIELDS",
    "op_display_name",
    "iter_block_ops",
    "match_ops",
    "stats_spec",
    "program_op_costs",
    "attribute_events",
    "build_record",
    "device_profile",
    "host_profile",
    "export_record",
    "render_table",
    "render_rollup",
    "PEAK_MM_TFLOPS",
    "PEAK_BW_GBS",
    "record_tensor_stats",
    "last_tensor_stats",
    "localize_nonfinite",
    "write_provenance",
    "last_provenance",
]

# reserved key smuggling the stacked [n, 4] stats array out of the jitted
# step through the created-persistables dict ('@' keeps it disjoint from any
# legal var name, like registry.EMPTY_VAR_NAME)
TENSOR_STATS_KEY = "@TENSOR_STATS@"
STAT_FIELDS = ("mean", "std", "absmax", "nonfinite")

_lock = threading.Lock()
_last_tensor_stats = None
_last_provenance = None


# ---------------------------------------------------------------------------
# op identity
# ---------------------------------------------------------------------------


def op_display_name(op):
    """'<type>:<first real output var>' — fluid ops are anonymous, so the
    first output is the stable instance handle (same identity the nested
    named_scope writes into the HLO, registry.op_output_scope)."""
    from ..ops.registry import EMPTY_VAR_NAME

    for name in op.output_arg_names:
        if name != EMPTY_VAR_NAME:
            return "%s:%s" % (op.type, name)
    return op.type


def iter_block_ops(block):
    """Yield every op of a block INCLUDING control-flow sub-blocks (While/
    cond bodies live as Block-valued attrs — the instrumentation pass must
    see them the way the reference's op walk saw sub-block descs)."""
    from .. import framework

    for op in block.ops:
        yield op
        for v in op.attrs.values():
            if isinstance(v, framework.Block):
                for sub in iter_block_ops(v):
                    yield sub


def match_ops(ops, pattern):
    """Ops whose display name, type, or any output var name matches the
    glob (fnmatch, case-sensitive). `ops` is an iterable of Operators or a
    Block (walked recursively)."""
    from .. import framework
    from ..ops.registry import EMPTY_VAR_NAME

    if isinstance(ops, framework.Block):
        ops = iter_block_ops(ops)
    out = []
    for op in ops:
        names = [op_display_name(op), op.type] + [
            n for n in op.output_arg_names if n != EMPTY_VAR_NAME
        ]
        if any(fnmatch.fnmatchcase(n, pattern) for n in names):
            out.append(op)
    return out


def stats_spec(ops, pattern):
    """((display_name, first_output_var), ...) for FLAGS_tensor_stats
    matches — what executor._CompiledBlock instruments at trace time."""
    from ..ops.registry import EMPTY_VAR_NAME

    spec = []
    seen = set()
    for op in match_ops(ops, pattern):
        for name in op.output_arg_names:
            if name != EMPTY_VAR_NAME:
                if name not in seen:
                    seen.add(name)
                    spec.append((op_display_name(op), name))
                break
    return tuple(spec)


# ---------------------------------------------------------------------------
# leg 1: cost attribution
# ---------------------------------------------------------------------------


def program_op_costs(ops, aval_of):
    """{display name: (flops, bytes)} from the Program-level counting model
    (parallel.partition.analytic_op_flops_bytes — the same numbers the pp
    partitioner balances on). `aval_of(name)` returns an object with
    .shape/.dtype or None for unknown vars."""
    from ..ops.registry import EMPTY_VAR_NAME
    from ..parallel import partition as _part

    costs = {}
    for op in ops:
        in_avals = {
            slot: [aval_of(n) if n != EMPTY_VAR_NAME else None for n in names]
            for slot, names in op.inputs.items()
        }
        out_avals = {
            slot: [aval_of(n) if n != EMPTY_VAR_NAME else None for n in names]
            for slot, names in op.outputs.items()
        }
        flops, nbytes = _part.analytic_op_flops_bytes(op.type, in_avals, out_avals)
        key = op_display_name(op)
        f0, b0 = costs.get(key, (0, 0))
        costs[key] = (f0 + flops, b0 + nbytes)
    return costs


def block_aval_resolver(block, feed_avals=None):
    """aval_of(name) over a block's declared vars, with -1 (batch) dims
    resolved from the fed batch size when one is known."""
    import numpy as np

    feed_avals = feed_avals or {}
    batch = None
    for a in feed_avals.values():
        if getattr(a, "shape", None):
            batch = int(a.shape[0])
            break

    class _A(object):
        __slots__ = ("shape", "dtype")

        def __init__(self, shape, dtype):
            self.shape = shape
            self.dtype = dtype

    def aval_of(name):
        a = feed_avals.get(name)
        if a is not None:
            return a
        try:
            v = block._var_recursive(name)
        except KeyError:
            return None
        if v.shape is None or v.dtype is None:
            return None
        shape = tuple(
            (batch if (d == -1 and batch is not None) else abs(int(d)))
            for d in v.shape
        )
        try:
            dtype = np.dtype("uint16" if v.dtype == "bfloat16" else v.dtype)
        except TypeError:
            return None
        return _A(shape, dtype)

    return aval_of


def attribute_events(events, hlo_text, aux=None):
    """Fold per-HLO-instruction device timings ({instr: [count, total_ms,
    min_ms, max_ms]}, profiler.device_instr_events shape) onto fluid op
    instances via the compiled HLO's op_name metadata. Returns {key: row}
    where key is '<type>:<output>' when the instance is known, '<type>' when
    only the type-level scope matched, or 'hlo:<opcode>' for unattributed
    instructions (arg copies, partitioner-inserted collectives). `aux` maps
    instr -> {"flops", "bytes"} (xplane cost analysis) when available."""
    from .. import profiler as _prof

    attribution = _prof._hlo_op_attribution(hlo_text) if hlo_text else {}
    aux = aux or {}
    table = {}
    for instr, (count, total, mn, mx) in events.items():
        # event names can carry extra dotted suffixes beyond the HLO name
        # (fusion clones, xplane numbering): strip one suffix, then all.
        # aux shares the events' exact names (same xplane merge), so cost
        # analysis never falls back — that would double-count an instruction
        a = aux.get(instr)
        att = None
        for cand in (instr, instr.rsplit(".", 1)[0], instr.split(".")[0]):
            att = attribution.get(cand)
            if att is not None:
                break
        if att is not None:
            typ, out = att
            key = "%s:%s" % (typ, out) if out else typ
        else:
            typ = None
            key = "hlo:" + instr.split(".")[0]
        row = table.setdefault(
            key,
            {
                "op": key,
                "type": typ or key,
                "count": 0,
                "total_ms": 0.0,
                "min_ms": float("inf"),
                "max_ms": 0.0,
                "flops": 0,
                "bytes": 0,
            },
        )
        row["count"] += count
        row["total_ms"] += total
        row["min_ms"] = min(row["min_ms"], mn)
        row["max_ms"] = max(row["max_ms"], mx)
        if a:
            row["flops"] += int(a.get("flops", 0))
            row["bytes"] += int(a.get("bytes", 0))
    return table


def build_record(table, step_ms=None, source="xplane", step=None, costs=None):
    """Assemble the "op_profile" telemetry record from an attribute_events
    table. `costs` ({display: (flops, bytes)}, program_op_costs) fills FLOPs
    for rows the trace carried no cost analysis for. % of step is against
    `step_ms` when the caller measured one, else against the summed device
    time (self-normalized)."""
    rows = []
    total_ms = sum(r["total_ms"] for r in table.values())
    denom = step_ms if step_ms else total_ms
    for key in sorted(table, key=lambda k: -table[k]["total_ms"]):
        r = dict(table[key])
        if costs and not r["flops"]:
            f, b = costs.get(key, (0, 0))
            # also try the type-level key a type-only attribution collapsed to
            if not f and ":" not in key:
                f = sum(c[0] for k, c in costs.items() if k.startswith(key + ":"))
                b = sum(c[1] for k, c in costs.items() if k.startswith(key + ":"))
            r["flops"] = r["flops"] or int(f)
            r["bytes"] = r["bytes"] or int(b)
        r["total_ms"] = round(r["total_ms"], 4)
        r["mean_ms"] = round(r["total_ms"] / max(r["count"], 1), 4)
        r["min_ms"] = round(r["min_ms"], 4) if r["count"] else 0.0
        r["max_ms"] = round(r["max_ms"], 4)
        r["pct"] = round(100.0 * r["total_ms"] / denom, 2) if denom else 0.0
        rows.append(r)
    rec = {
        "kind": "op_profile",
        "source": source,
        "total_device_ms": round(total_ms, 4),
        "ops": rows,
    }
    if step_ms is not None:
        rec["step_ms"] = round(step_ms, 4)
    if step is not None:
        rec["step"] = step
    return rec


def device_profile(executor, log_dir, step_ms=None, block=None, feed_avals=None):
    """Leg-1 driver for a REAL device trace: per-op table from an xla_trace
    log dir + the executor's last compiled HLO. `block`/`feed_avals` enable
    the analytic FLOPs fallback for rows without xplane cost analysis.
    Returns the op_profile record (also exported when telemetry is active)."""
    from .. import profiler as _prof

    aux = {}
    events = _prof.device_instr_events(log_dir, aux=aux)
    hlo = executor.compiled_hlo()
    table = attribute_events(events, hlo, aux=aux)
    costs = None
    if block is not None:
        ops = [op for op in iter_block_ops(block)]
        costs = program_op_costs(ops, block_aval_resolver(block, feed_avals))
    rec = build_record(table, step_ms=step_ms, source="xplane", costs=costs)
    export_record(rec)
    return rec


def host_profile(table=None, step_ms=None, block=None, feed_avals=None):
    """Leg-1 driver from the HOST profiler's eager per-op events
    (FLAGS_profile_ops runs under the profiler record 'op/<display>' spans
    with a device sync per op — executor._PerOpProfiledBlock). The same
    record shape as device_profile, with source="host_events", for backends
    where xplane ProfileData is unavailable (the CPU test backend)."""
    from .. import profiler as _prof

    if table is None:
        table, _snapshot = _prof._aggregate()
    rows = {}
    for name, (count, total, mn, mx) in table.items():
        # profiler names are nested paths ('run/block0/op/<display>'); take
        # the op/ leaf and skip everything else
        if "op/" not in name:
            continue
        key = name.rsplit("op/", 1)[1]
        if not key or "/" in key:
            continue
        row = rows.setdefault(
            key,
            {
                "op": key,
                "type": key.split(":", 1)[0],
                "count": 0,
                "total_ms": 0.0,
                "min_ms": float("inf"),
                "max_ms": 0.0,
                "flops": 0,
                "bytes": 0,
            },
        )
        row["count"] += count
        row["total_ms"] += total
        row["min_ms"] = min(row["min_ms"], mn)
        row["max_ms"] = max(row["max_ms"], mx)
    costs = None
    if block is not None:
        ops = [op for op in iter_block_ops(block)]
        costs = program_op_costs(ops, block_aval_resolver(block, feed_avals))
    rec = build_record(rows, step_ms=step_ms, source="host_events", costs=costs)
    export_record(rec)
    return rec


def _fmt_flops(f):
    if not f:
        return "-"
    for unit in ("", "K", "M", "G", "T", "P"):
        if f < 1000 or unit == "P":
            return "%.4g%s" % (f, unit)
        f /= 1000.0


# roofline peaks for the Roof% column / headroom rollup: analytic defaults
# matching tools/mfu_audit.py; a record carrying "peak_tflops"/"peak_bw_gbs"
# (mfu_audit writes the measured-bandwidth variant) overrides them
PEAK_MM_TFLOPS = 192.0
PEAK_BW_GBS = 676.0


def _roofline_ms(row, peak_tflops, peak_bw_gbs):
    """Roofline minimum busy ms for one row — max of the compute leg
    (flops / peak matmul throughput) and the memory leg (bytes / peak HBM
    bandwidth); None when the row carries neither cost."""
    f = row.get("flops", 0) or 0
    b = row.get("bytes", 0) or 0
    if not f and not b:
        return None
    return max(f / (peak_tflops * 1e9), b / (peak_bw_gbs * 1e6))


def _row_roof_pct(r, peak_tflops, peak_bw_gbs):
    roof = _roofline_ms(r, peak_tflops, peak_bw_gbs)
    if roof is None or not r["total_ms"]:
        return "-"
    return "%.1f" % min(100.0 * roof / r["total_ms"], 100.0)


def render_table(record, top=20):
    """op_profile record -> the printable top-k table (shared by
    tools/op_profile.py and interactive use). Roof% is achieved fraction of
    the per-row roofline minimum (100 = nothing left to win)."""
    peak_tflops = record.get("peak_tflops", PEAK_MM_TFLOPS)
    peak_bw_gbs = record.get("peak_bw_gbs", PEAK_BW_GBS)
    lines = [
        "---------------->    Op Profile (%s)    <----------------"
        % record.get("source", "?"),
        "%-44s %7s %10s %10s %8s %10s %6s %6s"
        % ("Op", "Count", "Total(ms)", "Mean(ms)", "FLOPs", "Bytes", "%",
           "Roof%"),
    ]
    for r in record.get("ops", [])[:top]:
        lines.append(
            "%-44s %7d %10.4f %10.4f %8s %10s %6.2f %6s"
            % (
                r["op"][:44],
                r["count"],
                r["total_ms"],
                r.get("mean_ms", r["total_ms"] / max(r["count"], 1)),
                _fmt_flops(r.get("flops", 0)),
                _fmt_flops(r.get("bytes", 0)),
                r.get("pct", 0.0),
                _row_roof_pct(r, peak_tflops, peak_bw_gbs),
            )
        )
    total = record.get("total_device_ms")
    if total is not None:
        tail = "total device ms: %.4f" % total
        if record.get("step_ms") is not None:
            tail += "   step ms: %.4f   coverage: %.1f%%" % (
                record["step_ms"],
                100.0 * total / record["step_ms"] if record["step_ms"] else 0.0,
            )
        lines.append(tail)
    return "\n".join(lines)


def render_rollup(record, top=10):
    """Category (op type) rollup ranked by roofline HEADROOM — the busy ms
    above each category's roofline minimum, i.e. the time a kernel
    substitution could still win back. Raw ms ranks a category that is big
    but already optimal above one that is smaller but 3x off roofline;
    headroom is the attack-order signal. Rows without cost analysis are
    assumed AT roofline (they claim no headroom)."""
    peak_tflops = record.get("peak_tflops", PEAK_MM_TFLOPS)
    peak_bw_gbs = record.get("peak_bw_gbs", PEAK_BW_GBS)
    cats = {}
    for r in record.get("ops", []):
        c = cats.setdefault(
            r.get("type") or r["op"],
            {"count": 0, "total_ms": 0.0, "roof_ms": 0.0},
        )
        c["count"] += r["count"]
        c["total_ms"] += r["total_ms"]
        roof = _roofline_ms(r, peak_tflops, peak_bw_gbs)
        c["roof_ms"] += min(
            roof if roof is not None else r["total_ms"], r["total_ms"]
        )
    lines = [
        "----------------> Category rollup (by headroom) <----------------",
        "%-28s %7s %10s %12s %12s %6s"
        % ("Category", "Count", "Total(ms)", "Roofline(ms)", "Headroom(ms)",
           "Roof%"),
    ]
    ranked = sorted(
        cats.items(), key=lambda kv: kv[1]["roof_ms"] - kv[1]["total_ms"]
    )
    for name, c in ranked[:top]:
        headroom = c["total_ms"] - c["roof_ms"]
        pct = 100.0 * c["roof_ms"] / c["total_ms"] if c["total_ms"] else 0.0
        lines.append(
            "%-28s %7d %10.4f %12.4f %12.4f %6.1f"
            % (name[:28], c["count"], c["total_ms"], c["roof_ms"], headroom,
               pct)
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# telemetry plumbing shared by all three legs
# ---------------------------------------------------------------------------


def _current_step():
    from . import stepstats as _ss

    if _ss.active():
        return _ss.collector()._step
    return None


def export_record(record):
    """Ship any opprof record through the telemetry JSONL path when
    FLAGS_telemetry_dir is configured; a no-op sink otherwise. Never raises
    (same contract as the step-record path)."""
    try:
        from . import stepstats as _ss

        if not _ss.active():
            return False
        col = _ss.collector()
        if record.get("step") is None:
            record["step"] = col._step
        exp = col._get_exporter()
        if exp is None:
            return False
        exp.write_record(record)
        return True
    except Exception as e:  # telemetry must never break the run
        if not getattr(export_record, "_warned", False):
            export_record._warned = True
            print(
                "opprof export failed (disabled for this message): %r" % e,
                file=sys.stderr,
            )
        return False


# ---------------------------------------------------------------------------
# leg 2: tensor stats
# ---------------------------------------------------------------------------


def record_tensor_stats(names, stats, step=None):
    """Executor hook: `names` are the instrumented op display names (trace
    order), `stats` the host [n, 4] float array popped off the created dict
    (columns = STAT_FIELDS). Stashes the last reading, streams a
    "tensor_stats" record, and maintains labelled registry gauges."""
    global _last_tensor_stats

    per_op = {}
    for name, row in zip(names, stats):
        per_op[name] = {
            "mean": float(row[0]),
            "std": float(row[1]),
            "absmax": float(row[2]),
            "nonfinite": int(row[3]),
        }
    with _lock:
        _last_tensor_stats = per_op
    try:
        from . import registry as _registry

        reg = _registry.default_registry()
        for name, st in per_op.items():
            if math.isfinite(st["absmax"]):
                reg.gauge(
                    "tensor_stats/absmax", "per-op output abs-max (FLAGS_tensor_stats)"
                ).set(st["absmax"], op=name)
            reg.gauge(
                "tensor_stats/nonfinite",
                "per-op non-finite output count (FLAGS_tensor_stats)",
            ).set(st["nonfinite"], op=name)
    except Exception:
        pass
    export_record({"kind": "tensor_stats", "step": step, "ops": per_op})
    return per_op


def last_tensor_stats():
    """Most recent per-op stats dict from an instrumented run (or None)."""
    with _lock:
        return dict(_last_tensor_stats) if _last_tensor_stats else None


# ---------------------------------------------------------------------------
# leg 3: NaN provenance
# ---------------------------------------------------------------------------


def _host_stats(value):
    """Small host-side description of one array for the provenance record:
    finite-mean/std, absmax, nonfinite count, shape, dtype."""
    import numpy as np

    a = np.asarray(value)
    d = {"shape": list(a.shape), "dtype": str(a.dtype)}
    if a.dtype.kind == "f" and a.size:
        finite = np.isfinite(a)
        n_bad = int(a.size - finite.sum())
        d["nonfinite"] = n_bad
        if n_bad < a.size:
            good = a[finite]
            d["mean"] = float(good.mean())
            d["std"] = float(good.std())
            d["absmax"] = float(np.abs(good).max())
    return d


def _clean_attrs(attrs):
    """Scalar/short attrs only — sub-blocks and role metadata add noise."""
    from .. import framework

    out = {}
    for k, v in sorted(attrs.items()):
        if k.startswith("__") or k == framework.OpRole.OP_ROLE_KEY:
            continue
        if isinstance(v, framework.Block):
            continue
        if isinstance(v, (bool, int, float, str)):
            out[k] = v
        elif isinstance(v, (list, tuple)) and len(v) <= 8 and all(
            isinstance(x, (bool, int, float, str)) for x in v
        ):
            out[k] = list(v)
    return out


def localize_nonfinite(ops, env, rng_key, step=None):
    """Interpreter-mode NaN localization: replay `ops` in Program order over
    a copy of `env` (name -> array: the step's feeds + pre-step state),
    checking each op's float outputs for non-finite values. The eager walk
    costs one device sync per op — a diagnosis path, never a training path —
    but consumes the SAME rng key trajectory as the compiled step
    (registry.lower_ops splits per stochastic op in op order), so the replay
    reproduces the failure exactly. Returns the provenance dict for the
    first offending op, or None if the replay stays finite. Host ops
    (send/recv) are skipped — replaying RPC side effects while diagnosing
    would corrupt the cluster's state."""
    import jax.numpy as jnp

    from ..ops import registry as _reg
    from ..ops.registry import EMPTY_VAR_NAME

    env = dict(env)
    ctx = _reg.LowerCtx(rng_key)
    for idx, op in enumerate(ops):
        opdef = _reg.get(op.type)
        if opdef.skip_exec or opdef.is_host:
            continue
        in_vals = {
            n: env.get(n)
            for n in op.input_arg_names
            if n != EMPTY_VAR_NAME and env.get(n) is not None
        }
        _reg.lower_ops(ctx, [op], env)
        bad = []
        for n in op.output_arg_names:
            if n == EMPTY_VAR_NAME:
                continue
            v = env.get(n)
            if v is None:
                continue
            a = jnp.asarray(v)
            if jnp.issubdtype(a.dtype, jnp.floating) and not bool(
                jnp.isfinite(a).all()
            ):
                bad.append(n)
        if bad:
            return {
                "kind": "nan_provenance",
                "step": step,
                "op_index": idx,
                "op_type": op.type,
                "op": op_display_name(op),
                "outputs": bad,
                "output_stats": {n: _host_stats(env[n]) for n in bad},
                "input_stats": {n: _host_stats(v) for n, v in in_vals.items()},
                "attrs": _clean_attrs(op.attrs),
            }
    return None


def write_provenance(record, reason="nan_guard"):
    """Record a localized NaN: health counter, telemetry record when
    configured, one stderr line always (the operator asked for provenance —
    it must surface even without a telemetry dir), and the in-process
    stash read by last_provenance()."""
    global _last_provenance

    rec = dict(record)
    rec["kind"] = "nan_provenance"
    rec["reason"] = reason
    if rec.get("step") is None:
        step = _current_step()
        if step is not None:
            rec["step"] = step
    with _lock:
        _last_provenance = rec
    try:
        from ..resilience import health as _health

        _health.incr("nan_provenance")
    except Exception:
        pass
    export_record(rec)
    print(
        "[nan_provenance] first non-finite output at op #%s %s (%s) "
        "outputs=%s step=%s"
        % (
            rec.get("op_index"),
            rec.get("op"),
            reason,
            rec.get("outputs"),
            rec.get("step"),
        ),
        file=sys.stderr,
        flush=True,
    )
    return rec


def last_provenance():
    """Most recent NaN provenance record of this process (or None)."""
    with _lock:
        return dict(_last_provenance) if _last_provenance else None
