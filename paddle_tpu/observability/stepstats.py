"""Per-step runtime statistics (StepStats), collected from the executor stack.

Hook points (all behind `active()` — one dict lookup when telemetry is off,
so the disabled path costs nothing measurable against a millisecond step):

- Executor.run / ParallelExecutor.run call `collector().record_step(...)`
  with the step's host wall time, compile-cache hit/miss, NaN-guard
  verdict, and (for pipelined blocks) the pp schedule parameters;
- py_reader.PyReader.next_batch reports time spent BLOCKED on the staging
  queue via `add_feed_stall()` — attributed to the next recorded step
  (that is exactly the time the device would have idled waiting for data);
- resilience.health counters ride the shared registry (health.py shim), so
  retry/backoff/NaN events appear in the same snapshots.

Pipeline-bubble measurement: with t(m) = c + (m+pp-1)·τ (GPipe/1F1B step
model, docs/parallelism.md), a single microbatch count m cannot separate
the per-tick time τ from the fixed overhead c — so the collector keeps
per-(pp, schedule, m) minimum step times and, once two m groups exist,
computes τ from the two-m slope and the bubble 1 - m·τ/t(m) for the
smallest m. This is the SAME estimator bench.py's run_pp_bench uses for
MULTICHIP_PP.json (measured 0.459 vs analytic 0.429 on the dp2×pp4 bench),
so the runtime gauge `pp/bubble_measured` is directly comparable to the
bench number. Until a second m group exists, only the analytic gauge
`pp/bubble_analytic` = (pp-1)/(m+pp-1) is published. Min-over-steps is the
right aggregate here: harness noise is one-sided (stalls only ever ADD
time), the same argument bench.py makes for its min-over-windows headline.
"""

import sys
import threading
import time
from collections import deque

from . import registry as _registry

__all__ = [
    "StepStats",
    "StepStatsCollector",
    "collector",
    "active",
    "analytic_bubble",
]


def analytic_bubble(pp, n_micro):
    """Classic GPipe/1F1B fill-drain bubble fraction (pp-1)/(m+pp-1); both
    schedules share it (1F1B changes liveness, not the bubble)."""
    return (pp - 1) / float(n_micro + pp - 1)


class StepStats:
    """One recorded executor step (possibly a k-step multi-step call)."""

    __slots__ = (
        "step", "ts", "wall_ms", "n_steps", "feed_stall_ms", "cache_hit",
        "nan_trip", "pp", "n_micro", "schedule", "loss", "training",
        "items", "item_unit",
    )

    def __init__(self, step, ts, wall_ms, n_steps=1, feed_stall_ms=0.0,
                 cache_hit=True, nan_trip=False, pp=None, n_micro=None,
                 schedule=None, loss=None, training=True, items=0,
                 item_unit="img"):
        self.step = step
        self.ts = ts
        self.wall_ms = wall_ms
        self.n_steps = n_steps
        self.feed_stall_ms = feed_stall_ms
        self.cache_hit = cache_hit
        self.nan_trip = nan_trip
        self.pp = pp
        self.n_micro = n_micro
        self.schedule = schedule
        self.loss = loss
        self.training = training
        self.items = items
        self.item_unit = item_unit

    def to_dict(self):
        d = {
            "kind": "step",
            "step": self.step,
            "ts": self.ts,
            "wall_ms": round(self.wall_ms, 4),
            "n_steps": self.n_steps,
            "feed_stall_ms": round(self.feed_stall_ms, 4),
            "cache_hit": self.cache_hit,
            "nan_trip": self.nan_trip,
            "training": self.training,
        }
        if self.pp:
            d["pp"] = self.pp
            d["n_micro"] = self.n_micro
            d["schedule"] = self.schedule
        if self.loss is not None:
            d["loss"] = self.loss
        if self.items:
            d["items"] = self.items
            d["item_unit"] = self.item_unit
        return d


def _flags():
    from .. import flags as f

    return f.get_flags(
        ("telemetry_dir", "telemetry_interval_steps", "telemetry_log_every")
    )


def active():
    """Cheap per-run gate: telemetry is on iff an export dir or the periodic
    health line is configured (FLAGS_telemetry_dir /
    FLAGS_telemetry_log_every), or a collector was force-enabled in code."""
    f = _flags()
    return bool(f["telemetry_dir"]) or f["telemetry_log_every"] > 0 or \
        _collector_forced


_collector_forced = False


class StepStatsCollector:
    def __init__(self, registry=None, window=1024):
        self._lock = threading.Lock()
        self.registry = registry or _registry.default_registry()
        self.recent = deque(maxlen=window)
        self._step = 0
        self._pending_stall_ms = 0.0
        # (pp, schedule, n_micro) -> [count, total_ms, min_ms]
        self._pp_groups = {}
        self._exporter = None
        self._exporter_dir = None
        self._last_health = {}
        self._last_line_ts = None
        self._last_line_step = 0
        self._m = {
            "steps": self.registry.counter(
                "steps_total", "training steps recorded"),
            "step_ms": self.registry.histogram(
                "step_ms", "per-step host wall time (ms)"),
            "stall_ms": self.registry.counter(
                "input/feed_stall_ms_total",
                "time blocked waiting on the input pipeline (ms)"),
            "cache_hits": self.registry.counter(
                "compile_cache/hits", "executor compile-cache hits"),
            "cache_misses": self.registry.counter(
                "compile_cache/misses",
                "executor compile-cache misses (trace+compile paid)"),
            "nan_trips": self.registry.counter(
                "nan_guard/trips", "NaN/Inf step-guard activations"),
            "items": self.registry.counter(
                "goodput/items_total",
                "rows/images/tokens processed, by unit (slo.GoodputSentinel "
                "divides the windowed delta by wall time for MFU-online)"),
        }

    # ---- hook API -----------------------------------------------------
    def add_feed_stall(self, ms):
        """Called by PyReader.next_batch with the time it spent blocked on
        the staging queue; folded into the NEXT recorded step."""
        with self._lock:
            self._pending_stall_ms += ms
        self._m["stall_ms"].inc(ms)

    def record_step(self, wall_ms, n_steps=1, cache_hit=True, nan_trip=False,
                    pp=None, n_micro=None, schedule=None, loss=None,
                    training=True, items=0, item_unit="img"):
        """One executor run. `n_steps` > 1 for multi-step (steps_per_run)
        calls: counters advance by k, per-step time is wall/k. `items` is
        the number of rows/images/tokens the run processed — it feeds the
        `goodput/items_total` counter the slo.GoodputSentinel divides by
        wall time for the live MFU-online gauge."""
        now = time.time()
        with self._lock:
            stall = self._pending_stall_ms
            self._pending_stall_ms = 0.0
            self._step += n_steps
            step = self._step
        st = StepStats(
            step, now, wall_ms, n_steps=n_steps, feed_stall_ms=stall,
            cache_hit=cache_hit, nan_trip=nan_trip, pp=pp, n_micro=n_micro,
            schedule=schedule, loss=loss, training=training, items=items,
            item_unit=item_unit,
        )
        per_step_ms = wall_ms / max(n_steps, 1)
        if training:
            self._m["steps"].inc(n_steps)
            self._m["step_ms"].observe(per_step_ms)
        if items:
            self._m["items"].inc(items, unit=item_unit)
        self._m["cache_hits" if cache_hit else "cache_misses"].inc()
        if nan_trip:
            self._m["nan_trips"].inc()
        if pp and n_micro:
            self._record_pp(pp, schedule or "gpipe", n_micro, per_step_ms)
        with self._lock:
            self.recent.append(st)
        self._export(st)
        self._maybe_log_line(st)
        return st

    # ---- pipeline bubble ----------------------------------------------
    def _record_pp(self, pp, schedule, n_micro, step_ms):
        with self._lock:
            g = self._pp_groups.setdefault(
                (pp, schedule, n_micro), [0, 0.0, float("inf")]
            )
            g[0] += 1
            g[1] += step_ms
            g[2] = min(g[2], step_ms)
        self.registry.gauge(
            "pp/bubble_analytic",
            "GPipe fill-drain bound (pp-1)/(m+pp-1) for the running config",
        ).set(round(analytic_bubble(pp, n_micro), 4))
        est = self.bubble_estimate()
        if est is not None:
            self.registry.gauge(
                "pp/bubble_measured",
                "two-m-slope runtime bubble (bench.py run_pp_bench estimator)",
            ).set(round(max(0.0, min(1.0, est["bubble"])), 4))

    def bubble_estimate(self):
        """Two-m-slope bubble over the recorded (pp, schedule) groups, or
        None until two microbatch counts have been observed. Returns
        {pp, schedule, m1, m2, t1_ms, t2_ms, tick_ms, bubble, analytic}."""
        with self._lock:
            by_cfg = {}
            for (pp, sched, m), (_c, _tot, mn) in self._pp_groups.items():
                by_cfg.setdefault((pp, sched), []).append((m, mn))
        for (pp, sched), pts in sorted(by_cfg.items()):
            if len(pts) < 2:
                continue
            pts.sort()
            (m1, t1), (m2, t2) = pts[0], pts[-1]
            tau = (t2 - t1) / (m2 - m1)
            return {
                "pp": pp,
                "schedule": sched,
                "m1": m1,
                "m2": m2,
                "t1_ms": round(t1, 4),
                "t2_ms": round(t2, 4),
                "tick_ms": round(tau, 4),
                "bubble": round(1.0 - m1 * tau / t1, 4) if t1 > 0 else None,
                "analytic": round(analytic_bubble(pp, m1), 4),
            }
        return None

    # ---- export / logging ----------------------------------------------
    def _get_exporter(self):
        f = _flags()
        d = f["telemetry_dir"]
        if not d:
            return None
        if self._exporter is None or self._exporter_dir != d:
            from .export import TelemetryExporter

            if self._exporter is not None:
                self._exporter.close()
            self._exporter = TelemetryExporter(
                d,
                interval_steps=max(int(f["telemetry_interval_steps"]), 1),
                registry=self.registry,
            )
            self._exporter_dir = d
        return self._exporter

    def _export(self, st):
        exp = self._get_exporter()
        if exp is not None:
            exp.on_step(st.to_dict(), self)

    def flush(self):
        """Force the exporter's interval work (snapshot record, Prometheus
        file, rank-0 merge) now — run loops call this at epoch ends."""
        exp = self._get_exporter()
        if exp is not None:
            exp.flush(self)

    def _maybe_log_line(self, st):
        every = int(_flags()["telemetry_log_every"])
        if every <= 0:
            return
        with self._lock:
            due = st.step - self._last_line_step >= every
            if not due:
                return
            prev_ts, prev_step = self._last_line_ts, self._last_line_step
            self._last_line_ts, self._last_line_step = st.ts, st.step
            prev_health = self._last_health
        from ..resilience import health as _health

        h = _health.snapshot()
        with self._lock:
            self._last_health = dict(h)
        deltas = {
            k: v - prev_health.get(k, 0)
            for k, v in sorted(h.items())
            if v - prev_health.get(k, 0)
        }
        parts = [
            "step=%d" % st.step,
            "step_ms=%.2f" % (st.wall_ms / max(st.n_steps, 1)),
        ]
        if prev_ts is not None and st.ts > prev_ts:
            parts.append(
                "steps_per_s=%.2f" % ((st.step - prev_step) / (st.ts - prev_ts))
            )
        if st.loss is not None:
            parts.append("loss=%.6g" % st.loss)
        if st.feed_stall_ms:
            parts.append("stall_ms=%.2f" % st.feed_stall_ms)
        if st.pp:
            parts.append("pp=%d m=%d" % (st.pp, st.n_micro))
        for k, v in deltas.items():
            parts.append("%s=+%d" % (k, v))
        # the "is it alive" line (docs/observability.md): stderr so JSON
        # emitters on stdout (bench.py, the dist runners) stay parseable
        print("[telemetry] " + " ".join(parts), file=sys.stderr, flush=True)

    # ---- introspection --------------------------------------------------
    def snapshot(self):
        with self._lock:
            recent = list(self.recent)
        return {
            "step": self._step,
            "recent": [s.to_dict() for s in recent],
            "bubble": self.bubble_estimate(),
        }

    def reset(self):
        with self._lock:
            self.recent.clear()
            self._step = 0
            self._pending_stall_ms = 0.0
            self._pp_groups.clear()
            self._last_health = {}
            self._last_line_ts = None
            self._last_line_step = 0

    def close(self):
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
            self._exporter_dir = None


_collector = None
_collector_lock = threading.Lock()


def collector():
    """Process-wide StepStatsCollector (lazy singleton)."""
    global _collector
    if _collector is None:
        with _collector_lock:
            if _collector is None:
                _collector = StepStatsCollector()
    return _collector


def maybe_flush():
    """Flush a snapshot/Prometheus rewrite IF telemetry is on — the interval
    clock for subsystems with no training step to ride (the serving batcher
    every N dispatches, the online HotReloader after a swap). Never raises:
    telemetry must not fail the caller's hot path."""
    try:
        if active():
            collector().flush()
    except Exception:
        pass
