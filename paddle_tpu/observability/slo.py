"""Declarative SLOs, multi-window burn-rate alerting, and drift sentinels.

Alerting follows the Google SRE Workbook (ch. 5) multi-window
multi-burn-rate recipe rather than raw thresholds: an `SLO` declares an
objective (availability of a counter selector, or a latency threshold over
a histogram selector), and the `AlertEngine` evaluates each SLO's error
ratio over a SHORT and a LONG window per rule. An alert fires only when
both windows burn error budget faster than the rule's factor — the long
window proves the problem is real, the short window makes the alert
resolve quickly once the fault clears. Defaults are the Workbook's page
(5m/1h at 14.4x) and ticket (30m/6h at 6x) rules; windows, factors and the
clock are injectable so tests and `bench.py slo` run at compressed
timescales.

The engine reads windowed deltas from any history provider with a
`history(window_s=None) -> [(ts, snapshot)]` method: the fleet-merged
store of aggregate.FleetAggregator, or the in-process `LocalSampler` for
single-process training loops. Firing/resolving produces `AlertEvent`s
that

- update the `slo/alerts_firing` gauge and `slo/alert_events` counter,
- write one structured `[slo] {...}` JSON line to stderr,
- append to an optional JSONL file (`tools/timeline.py --alerts_path`
  renders fire->resolve pairs as a chrome-trace track), and
- trigger a flight-recorder bundle (reason "slo_alert") carrying the
  offending window's merged series, so the anomaly dump holds the exact
  numbers that fired the alert.

Sentinels ride the same evaluation loop and catch regressions no static
threshold sees: `DriftSentinel` (EWMA fast/slow step-time or token-latency
drift), `RetraceSentinel` (compile-cache miss counter moving after steady
state — a post-warmup retrace), and `GoodputSentinel` (tokens/s / img/s vs
a BENCH-recorded roofline, i.e. MFU-online, fed from the stepstats
counters). Everything is off by default: nothing evaluates unless an
engine is constructed and driven.
"""

import json
import sys
import time

from . import flightrec as _flightrec
from . import registry as _registry

__all__ = [
    "SLO",
    "AlertEngine",
    "AlertEvent",
    "BurnRateRule",
    "DEFAULT_RULES",
    "DriftSentinel",
    "GoodputSentinel",
    "LocalSampler",
    "RetraceSentinel",
    "window_delta",
]


# --------------------------------------------------------------- windows
def _counter_total(snapshot, name):
    rec = snapshot.get(name)
    if not rec or rec.get("kind") != "counter":
        return 0
    return sum(v for v in rec["values"].values()
               if isinstance(v, (int, float)))


def window_delta(history, now, window_s, name):
    """Delta of cumulative metric `name` over [now - window_s, now].

    `history` is ascending [(ts, snapshot)]. The current point is the
    newest snapshot at/before `now`; the baseline is the newest snapshot
    at/before `now - window_s`, falling back to the OLDEST snapshot when
    history is younger than the window (partial window — standard burn-rate
    behaviour while history warms up). Returns (delta_rec, span_s) with
    delta_rec shaped like a snapshot record, or (None, 0.0) when fewer than
    two usable points exist. Counter resets (a restarted replica) clamp to
    the current value instead of going negative."""
    cur = base = None
    for ts, snap in history:
        if ts <= now:
            if base is None or ts <= now - window_s:
                base = (ts, snap)
            cur = (ts, snap)
    if cur is None or base is None or cur[0] <= base[0]:
        return None, 0.0
    span_s = cur[0] - base[0]
    c = cur[1].get(name)
    b = base[1].get(name, None)
    if c is None:
        return None, 0.0
    if c["kind"] == "counter":
        bvals = (b or {}).get("values", {}) if b else {}
        values = {}
        for labels, v in c["values"].items():
            d = v - bvals.get(labels, 0)
            values[labels] = d if d >= 0 else v  # reset: restart from 0
        return {"kind": "counter", "values": values}, span_s
    if c["kind"] == "histogram":
        if (not b or b.get("kind") != "histogram"
                or list(b["buckets"]) != list(c["buckets"])):
            return dict(c), span_s
        counts = [x - y for x, y in zip(c["counts"], b["counts"])]
        if any(x < 0 for x in counts):  # reset mid-window
            return dict(c), span_s
        return {
            "kind": "histogram",
            "buckets": list(c["buckets"]),
            "counts": counts,
            "sum": c["sum"] - b["sum"],
            "count": c["count"] - b["count"],
            # cumulative histograms don't carry windowed extremes; the
            # lifetime ones are the best available clamp
            "min": c.get("min"),
            "max": c.get("max"),
        }, span_s
    return dict(c), span_s


# --------------------------------------------------------------- SLO
class SLO:
    """One declarative objective over a registry metric selector.

    Availability (counter selector)::

        SLO("availability", objective=0.999, counter="fleet/requests",
            bad={"code": "5"})           # label prefix match -> bad event
        SLO("errors", objective=0.999, counter="serving/m/requests",
            bad_counter="serving/m/errors")

    Latency threshold (histogram selector)::

        SLO("latency", objective=0.99, histogram="fleet/request_ms",
            threshold_ms=100)            # good = observation <= threshold

    `error_ratio(history, now, window_s)` returns the fraction of events in
    the window that violated the objective, or None when the window holds
    fewer than `min_events` events (no traffic must not fire alerts)."""

    def __init__(self, name, objective, counter=None, bad=None,
                 bad_counter=None, histogram=None, threshold_ms=None,
                 min_events=1, description=""):
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0, 1), got %r" % objective)
        if bool(counter) == bool(histogram):
            raise ValueError(
                "SLO %r needs exactly one of counter=/histogram=" % name)
        if counter and not (bad or bad_counter):
            raise ValueError(
                "counter SLO %r needs bad= label prefixes or bad_counter="
                % name)
        if histogram and threshold_ms is None:
            raise ValueError("histogram SLO %r needs threshold_ms=" % name)
        self.name = name
        self.objective = float(objective)
        self.counter = counter
        self.bad = dict(bad or {})
        self.bad_counter = bad_counter
        self.histogram = histogram
        self.threshold_ms = None if threshold_ms is None else float(threshold_ms)
        self.min_events = int(min_events)
        self.description = description

    @property
    def budget(self):
        """Allowed error ratio: 1 - objective."""
        return 1.0 - self.objective

    @property
    def selector(self):
        return self.counter or self.histogram

    def _is_bad(self, labels):
        if not self.bad:
            return False
        d = dict((k, v) for k, v in _registry._label_pairs(labels))
        return all(
            k in d and str(d[k]).startswith(str(prefix))
            for k, prefix in self.bad.items()
        )

    def error_ratio(self, history, now, window_s):
        delta, _span = window_delta(history, now, window_s, self.selector)
        if delta is None:
            return None
        if self.counter:
            total = sum(delta["values"].values())
            if total < self.min_events:
                return None
            if self.bad_counter:
                bad_delta, _ = window_delta(
                    history, now, window_s, self.bad_counter)
                bad = sum(bad_delta["values"].values()) if bad_delta else 0
            else:
                bad = sum(v for labels, v in delta["values"].items()
                          if self._is_bad(labels))
            return min(max(bad / total, 0.0), 1.0)
        count = delta.get("count") or 0
        if count < self.min_events:
            return None
        good = sum(
            c for ub, c in zip(delta["buckets"], delta["counts"])
            if ub <= self.threshold_ms + 1e-9
        )
        return min(max(1.0 - good / count, 0.0), 1.0)

    def to_dict(self):
        return {
            "name": self.name,
            "objective": self.objective,
            "selector": self.selector,
            "kind": "availability" if self.counter else "latency",
            "threshold_ms": self.threshold_ms,
        }


class BurnRateRule:
    """Fire when BOTH windows burn budget faster than `factor`."""

    def __init__(self, severity, short_s, long_s, factor):
        self.severity = severity
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.factor = float(factor)

    def to_dict(self):
        return {
            "severity": self.severity,
            "short_s": self.short_s,
            "long_s": self.long_s,
            "factor": self.factor,
        }

    def __repr__(self):  # stable: DEFAULT_RULES appear in API signatures
        return "BurnRateRule(%r, %g, %g, %g)" % (
            self.severity, self.short_s, self.long_s, self.factor
        )


# SRE Workbook ch.5: 14.4x over 5m+1h pages (2% of a 30d budget in 1h),
# 6x over 30m+6h tickets (5% of the budget in 6h)
DEFAULT_RULES = (
    BurnRateRule("page", 300.0, 3600.0, 14.4),
    BurnRateRule("ticket", 1800.0, 21600.0, 6.0),
)


class AlertEvent:
    """One fire or resolve transition."""

    def __init__(self, name, severity, state, ts, info=None, series=None):
        self.name = name          # SLO or sentinel name
        self.severity = severity  # page | ticket | drift | goodput | ...
        self.state = state        # "firing" | "resolved"
        self.ts = ts
        self.info = dict(info or {})
        self.series = series      # offending window's merged series (fire)

    def to_dict(self, with_series=False):
        d = {
            "kind": "alert",
            "name": self.name,
            "severity": self.severity,
            "event": "fired" if self.state == "firing" else "resolved",
            "ts": self.ts,
        }
        d.update(self.info)
        if with_series and self.series is not None:
            d["series"] = self.series
        return d


# --------------------------------------------------------------- engine
class AlertEngine:
    """Evaluates SLO burn rates + sentinels against a snapshot history.

    Driven either by FleetAggregator.add_listener(engine.on_snapshot) — the
    router wires this when slos/sentinels are passed — or by calling
    `evaluate(now)` directly on an injected clock (tests, bench)."""

    def __init__(self, slos=(), history=None, rules=DEFAULT_RULES,
                 registry=None, clock=time.time, out_path=None,
                 log_stderr=True, flightrec=True):
        self.slos = list(slos)
        self.rules = list(rules)
        self._history = history
        self._clock = clock
        self.out_path = out_path
        self.log_stderr = log_stderr
        self.flightrec = flightrec
        self._sentinels = []
        self._active = {}   # (name, severity) -> AlertEvent (firing)
        self.events = []    # every transition, in order
        reg = registry or _registry.default_registry()
        self._g_firing = reg.gauge(
            "slo/alerts_firing", "alerts currently firing (SLO + sentinel)"
        )
        self._g_firing.set(0)
        self._m_events = reg.counter(
            "slo/alert_events", "alert transitions by name/severity/event"
        )
        self._g_burn = reg.gauge(
            "slo/burn_rate", "latest burn rate per SLO and window"
        )

    def add_sentinel(self, sentinel):
        self._sentinels.append(sentinel)
        return sentinel

    # ---- transitions ------------------------------------------------------
    def _emit(self, ev):
        self.events.append(ev)
        self._g_firing.set(len(self._active))
        self._m_events.inc(
            name=ev.name, severity=ev.severity,
            event="fired" if ev.state == "firing" else "resolved",
        )
        line = json.dumps(ev.to_dict(), sort_keys=True)
        if self.log_stderr:
            sys.stderr.write("[slo] %s\n" % line)
        if self.out_path:
            try:
                with open(self.out_path, "a") as f:
                    f.write(json.dumps(ev.to_dict(with_series=True),
                                       sort_keys=True) + "\n")
            except OSError:
                pass
        if ev.state == "firing" and self.flightrec:
            # the bundle carries the exact windowed series that fired
            _flightrec.trigger(
                "slo_alert", name=ev.name, severity=ev.severity,
                series=ev.series, **ev.info
            )

    def _fire(self, key, now, info, series=None):
        if key in self._active:
            return None
        ev = AlertEvent(key[0], key[1], "firing", now, info, series)
        self._active[key] = ev
        self._emit(ev)
        return ev

    def _resolve(self, key, now, info):
        fired = self._active.pop(key, None)
        if fired is None:
            return None
        info = dict(info)
        info["fired_ts"] = fired.ts
        info["duration_s"] = round(now - fired.ts, 3)
        ev = AlertEvent(key[0], key[1], "resolved", now, info)
        self._emit(ev)
        return ev

    # ---- evaluation -------------------------------------------------------
    def on_snapshot(self, fs):
        """FleetAggregator listener: evaluate at the scrape's timestamp."""
        self.evaluate(now=fs.ts)

    def evaluate(self, now=None):
        """One tick: every SLO x rule, then every sentinel. Returns this
        tick's transitions (AlertEvents)."""
        now = self._clock() if now is None else now
        hist = self._history.history() if self._history is not None else []
        out = []
        for slo in self.slos:
            for rule in self.rules:
                r_short = slo.error_ratio(hist, now, rule.short_s)
                r_long = slo.error_ratio(hist, now, rule.long_s)
                budget = slo.budget
                b_short = None if r_short is None else r_short / budget
                b_long = None if r_long is None else r_long / budget
                if b_short is not None:
                    self._g_burn.set(
                        round(b_short, 4),
                        slo=slo.name, window="%ds" % int(rule.short_s),
                    )
                firing = (
                    b_short is not None and b_long is not None
                    and b_short > rule.factor and b_long > rule.factor
                )
                key = (slo.name, rule.severity)
                info = {
                    "slo": slo.to_dict(),
                    "rule": rule.to_dict(),
                    "burn_short": b_short,
                    "burn_long": b_long,
                }
                if firing:
                    series, _ = window_delta(
                        hist, now, rule.short_s, slo.selector)
                    ev = self._fire(key, now, info, series=series)
                else:
                    ev = self._resolve(key, now, info)
                if ev is not None:
                    out.append(ev)
        for s in self._sentinels:
            state, info, series = s.evaluate(hist, now)
            key = (s.name, s.severity)
            if state == "firing":
                ev = self._fire(key, now, info, series=series)
            elif state == "ok":
                ev = self._resolve(key, now, info)
            else:  # warming / hold: no transition either way
                ev = None
            if ev is not None:
                out.append(ev)
        self._g_firing.set(len(self._active))
        return out

    def firing(self):
        return list(self._active.values())

    def stats(self):
        return {
            "slos": [s.to_dict() for s in self.slos],
            "rules": [r.to_dict() for r in self.rules],
            "sentinels": [s.name for s in self._sentinels],
            "firing": [ev.to_dict() for ev in self._active.values()],
            "events_total": len(self.events),
        }


# --------------------------------------------------------------- sampler
class LocalSampler:
    """In-process history provider: snapshots a registry on demand. The
    AlertEngine's window store when there is no fleet to scrape (training
    loops, tests, the bench drift round)."""

    def __init__(self, registry=None, clock=time.time, maxlen=4096):
        from collections import deque

        self.registry = registry or _registry.default_registry()
        self._clock = clock
        self._history = deque(maxlen=maxlen)

    def sample(self, now=None):
        now = self._clock() if now is None else now
        snap = self.registry.snapshot()
        self._history.append((now, snap))
        return now, snap

    def history(self, window_s=None):
        items = list(self._history)
        if window_s is not None and items:
            cutoff = items[-1][0] - window_s
            items = [(t, s) for t, s in items if t >= cutoff]
        return items


# --------------------------------------------------------------- sentinels
class DriftSentinel:
    """EWMA drift detector over a histogram's per-tick mean — catches a
    step-time or token-latency regression (e.g. after a model hot swap)
    that never crosses any static threshold. A fast EWMA tracks the
    current level, a slow EWMA the baseline; firing when fast exceeds
    slow by `rel_threshold` (with hysteresis at half the threshold for
    resolve). Stationary streams never fire (tested)."""

    def __init__(self, name, histogram, alpha_fast=0.3, alpha_slow=0.03,
                 rel_threshold=0.5, warmup=8, min_count=3, severity="drift"):
        self.name = name
        self.histogram = histogram
        self.alpha_fast = float(alpha_fast)
        self.alpha_slow = float(alpha_slow)
        self.rel_threshold = float(rel_threshold)
        self.warmup = int(warmup)
        self.min_count = int(min_count)
        self.severity = severity
        self._last = None   # (sum, count) at previous tick
        self._fast = None
        self._slow = None
        self._ticks = 0
        self._firing = False

    def evaluate(self, hist, now):
        if not hist:
            return "hold", {}, None
        snap = hist[-1][1]
        rec = snap.get(self.histogram)
        if not rec or rec.get("kind") != "histogram":
            return "hold", {}, None
        cur = (rec["sum"], rec["count"])
        last, self._last = self._last, cur
        if last is None:
            return "hold", {}, None
        dsum = cur[0] - last[0]
        dcount = cur[1] - last[1]
        if dcount < self.min_count:
            return ("firing" if self._firing else "hold"), {}, None
        mean = dsum / dcount
        if self._fast is None:
            self._fast = self._slow = mean
        else:
            self._fast += self.alpha_fast * (mean - self._fast)
            self._slow += self.alpha_slow * (mean - self._slow)
        self._ticks += 1
        info = {
            "sentinel": "drift",
            "histogram": self.histogram,
            "fast_ms": round(self._fast, 4),
            "slow_ms": round(self._slow, 4),
            "ratio": round(self._fast / self._slow, 4) if self._slow else None,
        }
        if self._ticks < self.warmup or not self._slow or self._slow <= 0:
            return "hold", info, None
        ratio = self._fast / self._slow
        if ratio > 1.0 + self.rel_threshold:
            self._firing = True
        elif ratio < 1.0 + self.rel_threshold / 2.0:
            self._firing = False
        series = {"kind": "histogram_mean", "mean_ms": round(mean, 4)}
        return ("firing" if self._firing else "ok"), info, series


class RetraceSentinel:
    """Post-warmup retrace detector: once a compile-miss style counter has
    been quiet for `steady_ticks`, ANY further movement fires — a retrace
    after steady state means the compile cache is being invalidated in
    production (shape drift, eviction, a bad hot swap)."""

    def __init__(self, name="retrace", counter="compile_cache/misses",
                 steady_ticks=5, severity="drift"):
        self.name = name
        self.counter = counter
        self.steady_ticks = int(steady_ticks)
        self.severity = severity
        self._last = None
        self._quiet = 0
        self._armed = False
        self._firing = False

    def evaluate(self, hist, now):
        if not hist:
            return "hold", {}, None
        total = _counter_total(hist[-1][1], self.counter)
        last, self._last = self._last, total
        if last is None:
            return "hold", {}, None
        delta = total - last
        info = {"sentinel": "retrace", "counter": self.counter,
                "delta": delta, "total": total, "armed": self._armed}
        if delta <= 0:
            self._quiet += 1
            if self._quiet >= self.steady_ticks:
                self._armed = True
            if self._firing and self._quiet >= 2:
                self._firing = False
            return ("firing" if self._firing else
                    ("ok" if not self._firing and self._armed else "hold")), \
                info, None
        was_armed = self._armed
        self._quiet = 0
        if was_armed:
            self._firing = True
            return "firing", info, {"kind": "counter_delta", "delta": delta}
        return "hold", info, None  # still warming up: first compiles are fine


class GoodputSentinel:
    """Live goodput vs a BENCH-recorded roofline (MFU-online): reads the
    delta of an item counter (gen tokens, stepstats items/images) between
    the last two snapshots, publishes `slo/goodput_per_s{unit=}` and
    `slo/goodput_vs_roofline{unit=}` gauges, and — when `min_frac` is set —
    fires once sustained goodput falls below that fraction of roofline."""

    def __init__(self, name, counter, roofline_per_s, unit="tokens",
                 scale=1.0, min_frac=None, warmup=2, severity="goodput",
                 registry=None):
        self.name = name
        self.counter = counter
        self.roofline_per_s = float(roofline_per_s)
        self.unit = unit
        self.scale = float(scale)
        self.min_frac = None if min_frac is None else float(min_frac)
        self.warmup = int(warmup)
        self.severity = severity
        self.registry = registry or _registry.default_registry()
        self._last = None  # (ts, total)
        self._ticks = 0
        self._firing = False
        self.last_per_s = None
        self.last_frac = None

    def evaluate(self, hist, now):
        if not hist:
            return "hold", {}, None
        ts, snap = hist[-1]
        total = _counter_total(snap, self.counter)
        last, self._last = self._last, (ts, total)
        if last is None or ts <= last[0]:
            return "hold", {}, None
        per_s = max(total - last[1], 0) * self.scale / (ts - last[0])
        frac = per_s / self.roofline_per_s if self.roofline_per_s else 0.0
        self.last_per_s = per_s
        self.last_frac = frac
        self.registry.gauge(
            "slo/goodput_per_s", "observed goodput (items/s) by unit"
        ).set(round(per_s, 3), unit=self.unit, name=self.name)
        self.registry.gauge(
            "slo/goodput_vs_roofline",
            "goodput as a fraction of the BENCH roofline (MFU-online)",
        ).set(round(frac, 4), unit=self.unit, name=self.name)
        self._ticks += 1
        info = {"sentinel": "goodput", "counter": self.counter,
                "per_s": round(per_s, 3), "roofline_per_s": self.roofline_per_s,
                "frac": round(frac, 4), "unit": self.unit}
        if self.min_frac is None or self._ticks <= self.warmup:
            return "hold", info, None
        if frac < self.min_frac:
            self._firing = True
        elif frac >= self.min_frac:
            self._firing = False
        return ("firing" if self._firing else "ok"), info, None
