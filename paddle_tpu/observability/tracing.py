"""Dapper-style distributed request tracing for the serving fleet.

One request crosses four queueing layers (router -> replica HTTP handler ->
batcher/scheduler -> engine), usually across processes. The aggregate
metrics (registry histograms) can say p99 regressed; this module says WHERE
one request's time went, by threading a TraceContext through every hop:

- **context**: (trace_id, span_id) propagates between processes in the
  ``X-Fleet-Trace: <trace_id>-<span_id>`` header (parse_header /
  Span.header()); within a process either explicitly (``span.child(...)``)
  or implicitly through the thread-local set by ``activate(span)`` — how the
  batcher's dispatcher thread hands the engine a parent without the engine
  API knowing about tracing.
- **spans**: Span.end() freezes one record {trace, span, parent, name, pid,
  host, tid, ts, dur_ms, status, tags, events}. Records land in a bounded
  per-process ring (the flight recorder's lookback — observability/
  flightrec.py) and, per the tail-sampling decision below, in per-process
  rotation-safe JSONL shards ``trace-host<h>-p<pid>.jsonl`` under
  FLAGS_trace_dir (same append/rotate discipline as export.py's telemetry
  shards; read them back with load_spans / export.read_records). Export is
  asynchronous: the request thread serializes its kept segment (a few us,
  paid evenly — batching serialization in the writer would burst the GIL
  onto in-flight requests) and appends the blob to a deque; an IO-only
  daemon writer drains and flushes every ~20ms, so shards survive SIGKILL
  with at most one drain interval of loss and tracing-on p99 stays inside
  the overhead budget.
- **tail sampling**: spans buffer in their local *segment* (all spans this
  process contributes to one trace) until the segment root ends, then the
  whole segment is kept or dropped at once. Error spans, spans slower than
  FLAGS_trace_slow_ms, and force_keep()'d spans (hedges, hot-swaps) always
  keep their segment; OK segments are kept when
  ``keep_trace(trace_id, FLAGS_trace_sample)`` says so — a DETERMINISTIC
  hash of the trace id, so every process in the fleet makes the same call
  for the same trace without coordination, and a sampled trace is never
  half-exported.
- **off path**: with tracing disabled (neither FLAGS_trace_dir nor
  FLAGS_flightrec_dir set), start_span returns the process-wide NULL_SPAN
  singleton whose methods are no-ops — the serving hot loop allocates
  NOTHING per request (tests assert object identity), and outputs are
  bit-identical to a build that never imported this module.

Rendering: ``tools/timeline.py --trace_path`` turns shards into cross-
process chrome-trace tracks; ``tools/trace_view.py`` prints top-k slowest
traces and per-trace span trees with the critical path; ``tools/monitor.py``
shows live trace counters. docs/observability.md has the span catalog.
"""

import atexit
import glob
import itertools as _itertools
import json
import os
import random as _random
import threading
import time
import zlib
from collections import deque

__all__ = [
    "NULL_SPAN",
    "Span",
    "Tracer",
    "tracer",
    "reset",
    "current",
    "activate",
    "parse_header",
    "keep_trace",
    "load_spans",
    "TRACE_HEADER",
    "SHARD_PATTERN",
]

TRACE_HEADER = "X-Fleet-Trace"
SHARD_PATTERN = "trace-*.jsonl*"


class _NullSpan:
    """The disabled tracer's span: ONE process-wide singleton whose methods
    are no-ops, so the tracing-off hot path allocates nothing per request.
    Falsy, so ``if span:`` gates optional work."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    status = "ok"

    def child(self, name, **tags):
        return self

    def tag(self, **tags):
        return self

    def event(self, name, **attrs):
        return self

    def error(self, err):
        return self

    def force_keep(self):
        return self

    def end(self, status=None):
        return self

    def header(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        return False

    def __bool__(self):
        return False

    def __repr__(self):
        return "<NULL_SPAN>"


NULL_SPAN = _NullSpan()


# id generation avoids the per-span getrandom syscall: trace ids come from a
# process-seeded PRNG (uniqueness + a well-mixed sampling-hash input need
# unpredictability across processes, not crypto strength), span ids from a
# counter off a random start (uniqueness within one trace is enough).
# Random.getrandbits and itertools.count.__next__ are atomic under the GIL.
_id_rng = _random.Random(os.urandom(16))
_span_ctr = _itertools.count(int.from_bytes(os.urandom(4), "big"))


def _new_id(nbytes):
    return "%0*x" % (2 * nbytes, _id_rng.getrandbits(8 * nbytes))


def _next_span_id():
    return "%08x" % (next(_span_ctr) & 0xFFFFFFFF)


def parse_header(value):
    """``"<trace_id>-<span_id>"`` -> (trace_id, span_id), or None for
    anything malformed — tracing must never fail a request."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    try:
        int(parts[0], 16)
        int(parts[1], 16)
    except ValueError:
        return None
    return parts[0], parts[1]


def keep_trace(trace_id, sample):
    """The fleet-consistent OK-trace sampling decision: a deterministic hash
    of the trace id against `sample`, so every process keeps or drops the
    same traces without coordination (error/slow/hedged segments bypass
    this via _Segment.keep)."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    h = zlib.crc32(trace_id.encode("ascii", "replace")) & 0xFFFFFFFF
    return h / 4294967296.0 < sample


class _Segment:
    """Every span one process contributes to one trace (its local subtree).
    The tail-sampling unit: records buffer here until the segment root ends,
    then the whole segment is exported or dropped in one decision."""

    __slots__ = ("records", "keep", "decided", "kept")

    def __init__(self):
        self.records = []
        self.keep = False  # forced by error / slow / force_keep'd spans
        self.decided = False
        self.kept = False


class Span:
    """One timed operation in a trace. Ends at most once; ending freezes the
    record into the tracer's ring + its segment. Usable as a context manager
    (an exception marks the span error before ending it)."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "tags", "events", "status", "_t0_wall", "_t0", "_tid",
                 "_segment", "_is_root", "_ended")

    def __init__(self, tracer_, name, trace_id, parent_id, segment, is_root,
                 tags):
        self._tracer = tracer_
        self.name = name
        self.trace_id = trace_id
        self.span_id = _next_span_id()
        self.parent_id = parent_id
        self.tags = tags
        self.events = None
        self.status = "ok"
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()
        self._tid = threading.get_ident() & 0xFFFFFF
        self._segment = segment
        self._is_root = is_root
        self._ended = False

    # ---- annotation -------------------------------------------------------
    def child(self, name, **tags):
        return self._tracer.start_span(name, parent=self, **tags)

    def tag(self, **tags):
        self.tags.update(tags)
        return self

    def event(self, name, **attrs):
        """Timestamped point annotation (a Dapper log entry)."""
        e = {"name": name, "ts": time.time()}
        if attrs:
            e.update(attrs)
        if self.events is None:
            self.events = []
        self.events.append(e)
        return self

    def error(self, err):
        self.status = "error"
        self.tags.setdefault("error", repr(err))
        return self

    def force_keep(self):
        """Exempt this span's whole segment from OK-trace sampling (hedged
        requests, hot-swaps — rare events worth keeping every time)."""
        self._segment.keep = True
        return self

    # ---- lifecycle --------------------------------------------------------
    def end(self, status=None):
        if self._ended:
            return self
        self._ended = True
        if status is not None:
            self.status = status
        self._tracer._finish(self)
        return self

    def header(self):
        """The X-Fleet-Trace value carrying this span's context downstream."""
        return "%s-%s" % (self.trace_id, self.span_id)

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if ev is not None:
            self.error(ev)
        self.end()
        return False

    def __bool__(self):
        return True

    def __repr__(self):
        return "<Span %s %s/%s>" % (self.name, self.trace_id, self.span_id)


class _NoopActivation:
    __slots__ = ()

    def __enter__(self):
        return NULL_SPAN

    def __exit__(self, et, ev, tb):
        return False


_NOOP_ACTIVATION = _NoopActivation()


class _Activation:
    __slots__ = ("_local", "_span", "_prev")

    def __init__(self, local, span):
        self._local = local
        self._span = span

    def __enter__(self):
        self._prev = getattr(self._local, "span", NULL_SPAN)
        self._local.span = self._span
        return self._span

    def __exit__(self, et, ev, tb):
        self._local.span = self._prev
        return False


class Tracer:
    """Per-process span factory, ring buffer, sampler and shard writer.
    Normally built from flags via the module-level tracer(); tests construct
    directly. A tracer with enabled=False is the zero-allocation stub."""

    def __init__(self, out_dir="", sample=1.0, slow_ms=500.0, ring=4096,
                 enabled=True, max_bytes=64 << 20):
        from .export import _process_index

        self.enabled = bool(enabled)
        self.out_dir = out_dir or None
        self.sample = float(sample)
        self.slow_ms = float(slow_ms)
        self.ring = deque(maxlen=max(int(ring), 16))
        self.max_bytes = int(max_bytes)
        self._host = _process_index()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._fh = None
        self._shard_path = None
        self._q = deque()    # pending kept segments (append is the on-path cost)
        self._writer = None  # daemon thread draining the deque in batches
        self._stop = threading.Event()
        self._io_lock = threading.Lock()  # serializes _drain callers
        self._closed = False
        self._local = threading.local()
        self._m_spans = self._m_segments = None
        if self.enabled:
            from . import registry as _registry

            reg = _registry.default_registry()
            self._m_spans = reg.counter(
                "trace/spans", "spans ended, by status label"
            )
            self._m_segments = reg.counter(
                "trace/segments", "local trace segments by sampling decision"
            )

    # ---- span factory -----------------------------------------------------
    def start_span(self, name, parent=None, **tags):
        """Open a span. `parent` is a live Span (same-process child), an
        X-Fleet-Trace header string (cross-process child), or None (new
        trace). Returns NULL_SPAN when tracing is off."""
        if not self.enabled:
            return NULL_SPAN
        if isinstance(parent, Span):
            return Span(self, name, parent.trace_id, parent.span_id,
                        parent._segment, False, tags)
        trace_id = parent_id = None
        if isinstance(parent, str):
            ctx = parse_header(parent)
            if ctx is not None:
                trace_id, parent_id = ctx
        if trace_id is None:
            trace_id = _new_id(8)
        # a span entering from another process (or starting a trace) roots a
        # fresh local segment: the tail-sampling unit for THIS process
        return Span(self, name, trace_id, parent_id, _Segment(), True, tags)

    def current(self):
        """The thread's implicitly activated span (NULL_SPAN when none) —
        how tracing crosses an API that doesn't take a span parameter."""
        return getattr(self._local, "span", NULL_SPAN)

    def activate(self, span):
        """Context manager making `span` the thread's current() span."""
        if not self.enabled or span is NULL_SPAN:
            return _NOOP_ACTIVATION
        return _Activation(self._local, span)

    # ---- completion / sampling -------------------------------------------
    def _finish(self, span):
        dur_ms = (time.perf_counter() - span._t0) * 1e3
        rec = {
            "kind": "span",
            "trace": span.trace_id,
            "span": span.span_id,
            "parent": span.parent_id,
            "name": span.name,
            "pid": self._pid,
            "host": self._host,
            "tid": span._tid,
            "ts": span._t0_wall,
            "dur_ms": round(dur_ms, 3),
            "status": span.status,
        }
        if span.tags:
            rec["tags"] = span.tags
        if span.events:
            rec["events"] = span.events
        self.ring.append(rec)  # flight-recorder lookback: sampled or not
        self._m_spans.inc(status=span.status)
        seg = span._segment
        if span.status != "ok" or dur_ms >= self.slow_ms:
            seg.keep = True
        if seg.decided:
            # a child that outlived its segment root follows the decision
            if seg.kept:
                self._export((rec,))
            return
        seg.records.append(rec)
        if not span._is_root:
            return
        kept = seg.keep or keep_trace(span.trace_id, self.sample)
        seg.decided, seg.kept = True, kept
        records, seg.records = seg.records, []
        self._m_segments.inc(decision="kept" if kept else "dropped")
        if kept:
            self._export(records)

    _DRAIN_INTERVAL_S = 0.02

    def _export(self, records):
        """Hand a kept segment to the background writer. Serialization
        happens HERE: a few microseconds paid evenly on every request
        beats batching it in the writer, whose periodic GIL bursts would
        land on whichever request is in flight and spike the tail. The
        writer is IO-only."""
        if self.out_dir is None:
            return
        blob = "".join(json.dumps(rec) + "\n" for rec in records)
        self._q.append(blob)
        if self._writer is None:
            with self._lock:
                if self._writer is None and not self._closed:
                    self._writer = threading.Thread(
                        target=self._write_loop, name="trace-export",
                        daemon=True,
                    )
                    self._writer.start()

    def _write_loop(self):
        while not self._stop.wait(self._DRAIN_INTERVAL_S):
            self._drain()
        self._drain()  # final sweep after close() signals stop

    def _drain(self):
        """Write every pre-serialized blob queued so far, flush once.
        Thread-safe (writer thread, flush(), close() all call it)."""
        with self._io_lock:
            q = self._q
            if not q:
                return
            try:
                if self._fh is None:
                    os.makedirs(self.out_dir, exist_ok=True)
                    self._shard_path = os.path.join(
                        self.out_dir,
                        "trace-host%d-p%d.jsonl" % (self._host, self._pid),
                    )
                    self._fh = open(self._shard_path, "a")
                while q:
                    self._fh.write(q.popleft())
                # flush per drain batch so shards survive a SIGKILL'd
                # replica (loss window <= one drain interval)
                self._fh.flush()
                if self._fh.tell() >= self.max_bytes:
                    # same rotation discipline as the telemetry shards
                    self._fh.close()
                    os.replace(self._shard_path, self._shard_path + ".1")
                    self._fh = open(self._shard_path, "a")
            except OSError:
                pass  # a full disk must not fail the request being traced

    def flush(self):
        """Put every segment enqueued so far on disk, synchronously."""
        if self.out_dir is not None:
            self._drain()

    # ---- introspection ----------------------------------------------------
    def recent(self, n=None):
        """Newest-last span records from the ring (all ended spans, sampled
        or not) — the flight recorder's lookback window."""
        out = list(self.ring)
        return out if n is None else out[-int(n):]

    def close(self):
        """Drain the writer and close the shard. Safe to call twice."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            w = self._writer
        self._stop.set()
        if w is not None:
            w.join(5.0)
        self._drain()  # anything the writer missed (or no writer at all)
        with self._io_lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# ---- process singleton ----------------------------------------------------
_tracer = None
_tracer_lock = threading.Lock()


def _build():
    from .. import flags as _flags

    f = _flags.get_flags([
        "trace_dir", "trace_sample", "trace_slow_ms", "trace_ring",
        "flightrec_dir",
    ])
    # the ring must run for the flight recorder even when shard export is
    # off, so either flag enables span creation
    enabled = bool(f["trace_dir"]) or bool(f["flightrec_dir"])
    return Tracer(
        out_dir=f["trace_dir"],
        sample=f["trace_sample"],
        slow_ms=f["trace_slow_ms"],
        ring=f["trace_ring"],
        enabled=enabled,
    )


def tracer():
    """The process tracer, built from FLAGS_trace_* on first use. After
    set_flags, call reset() to rebuild."""
    global _tracer
    t = _tracer
    if t is None:
        with _tracer_lock:
            t = _tracer
            if t is None:
                t = _tracer = _build()
    return t


def current():
    return tracer().current()


def activate(span):
    return tracer().activate(span)


def reset():
    """Drop the process tracer so the next tracer() call re-reads flags
    (tests toggle FLAGS_trace_dir mid-process)."""
    global _tracer
    with _tracer_lock:
        t, _tracer = _tracer, None
    if t is not None:
        t.close()


def _atexit_drain():
    # the export writer is a daemon thread; drain it on clean interpreter
    # exit so a replica that simply returns from main loses no segments
    t = _tracer
    if t is not None:
        t.close()


atexit.register(_atexit_drain)


# ---- reading shards back --------------------------------------------------
def load_spans(path):
    """Span records from one JSONL shard file, or every ``trace-*.jsonl*``
    shard under a directory, ts-sorted. Torn trailing lines are skipped
    (export.read_records)."""
    from .export import read_records

    if os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, SHARD_PATTERN)))
    else:
        paths = [path]
    records = []
    for p in paths:
        records.extend(
            r for r in read_records(p) if r.get("kind") == "span"
        )
    records.sort(key=lambda r: (r.get("ts", 0), r.get("pid", 0)))
    return records
