"""AlexNet (reference benchmark/README.md:33-38 — the ms/batch speed-table
model; classic 5-conv + 3-fc topology with LRN after the first two convs)."""

from .. import layers

__all__ = ["alexnet"]


def alexnet(img, label, class_dim=1000, use_lrn=True):
    def conv(x, num_filters, filter_size, stride=1, padding=0, groups=1):
        return layers.conv2d(
            input=x,
            num_filters=num_filters,
            filter_size=filter_size,
            stride=stride,
            padding=padding,
            groups=groups,
            act="relu",
        )

    def maxpool(x):
        return layers.pool2d(
            input=x, pool_size=3, pool_stride=2, pool_type="max"
        )

    c1 = conv(img, 64, 11, stride=4, padding=2)
    if use_lrn:
        c1 = layers.lrn(input=c1, n=5, alpha=1e-4, beta=0.75)
    p1 = maxpool(c1)
    c2 = conv(p1, 192, 5, padding=2)
    if use_lrn:
        c2 = layers.lrn(input=c2, n=5, alpha=1e-4, beta=0.75)
    p2 = maxpool(c2)
    c3 = conv(p2, 384, 3, padding=1)
    c4 = conv(c3, 256, 3, padding=1)
    c5 = conv(c4, 256, 3, padding=1)
    p5 = maxpool(c5)
    flat = layers.reshape(p5, [0, -1])
    fc6 = layers.fc(input=layers.dropout(flat, 0.5), size=4096, act="relu")
    fc7 = layers.fc(input=layers.dropout(fc6, 0.5), size=4096, act="relu")
    out = layers.fc(input=fc7, size=class_dim, act="softmax")
    loss = layers.mean(layers.cross_entropy(input=out, label=label))
    acc = layers.accuracy(input=out, label=label)
    return loss, acc, out
