"""GRU attention seq2seq for NMT (reference benchmark/fluid/machine_translation.py
and tests/book/test_machine_translation.py: bi-GRU encoder, Bahdanau-style
attention decoder trained with a DynamicRNN, beam-search inference).

TPU-first notes: the decoder train loop is one lax.scan (DynamicRNN); the
beam-search infer loop is an XLA While writing id/score/parent tensor arrays
(decode_ops.py), with decoder state gathered by parent_idx each step —
everything compiles into a single computation, unlike the reference's
per-step executor round-trips through while_op/beam_search_op."""

import numpy as np

from .. import layers
from ..framework import default_main_program
from ..param_attr import ParamAttr

__all__ = ["encoder", "train_model", "infer_model"]


def _mask_from(src_len_name, maxlen, block=None):
    block = block or default_main_program().current_block()
    lens = block._var_recursive(src_len_name)
    return layers.sequence_mask(lens, maxlen=maxlen, dtype="float32")


def encoder(src_word, dict_size, emb_dim=32, hid_dim=32):
    """bi-GRU encoder over [B, T, 1] ids (ragged via @LEN companion)."""
    emb = layers.embedding(src_word, size=[dict_size, emb_dim])
    emb._len_name = src_word._len_name
    proj_f = layers.fc(emb, size=hid_dim * 3, num_flatten_dims=2)
    proj_b = layers.fc(emb, size=hid_dim * 3, num_flatten_dims=2)
    proj_f._len_name = emb._len_name
    proj_b._len_name = emb._len_name
    fwd = layers.dynamic_gru(proj_f, size=hid_dim)
    bwd = layers.dynamic_gru(proj_b, size=hid_dim, is_reverse=True)
    enc = layers.concat([fwd, bwd], axis=2)  # [B, T, 2H]
    enc._len_name = src_word._len_name
    # decoder boot: backward GRU's first step (summary of the sentence)
    boot = layers.fc(layers.sequence_first_step(bwd), size=hid_dim, act="tanh")
    return enc, boot


def _attention(state, enc, enc_proj, mask, hid_dim):
    """Additive attention: score = v·tanh(W_e enc + W_s st); returns [*, 2H]
    context. `mask` is [*, T] with 1 on valid source positions."""
    st_proj = layers.fc(state, size=hid_dim, bias_attr=False,
                        param_attr=ParamAttr(name="att_state_w"))
    st_exp = layers.unsqueeze(st_proj, [1])  # [*, 1, H]
    mix = layers.elementwise_add(enc_proj, st_exp)
    mix = layers.tanh(mix)
    scores = layers.fc(mix, size=1, num_flatten_dims=2, bias_attr=False,
                       param_attr=ParamAttr(name="att_score_w"))  # [*, T, 1]
    scores = layers.squeeze(scores, [2])
    neg = layers.scale(mask, scale=1e9, bias=-1e9)  # 0 valid, -1e9 invalid
    scores = layers.elementwise_add(scores, neg)
    att = layers.softmax(scores)  # [*, T]
    ctx = layers.reduce_sum(
        layers.elementwise_mul(enc, layers.unsqueeze(att, [2]), axis=0), dim=[1]
    )  # [*, 2H]
    return ctx


def train_model(src_word, trg_word, label, trg_len, dict_size,
                emb_dim=32, hid_dim=32):
    """Teacher-forced training net; label is trg shifted left. Returns the
    length-masked mean cross-entropy."""
    maxlen = src_word.shape[1]
    enc, boot = encoder(src_word, dict_size, emb_dim, hid_dim)
    enc_proj = layers.fc(enc, size=hid_dim, num_flatten_dims=2,
                         bias_attr=False, param_attr=ParamAttr(name="att_enc_w"))
    src_mask = _mask_from(src_word._len_name, maxlen)

    trg_emb = layers.embedding(trg_word, size=[dict_size, emb_dim],
                               param_attr=ParamAttr(name="trg_emb"))
    trg_emb._len_name = trg_len.name

    drnn = layers.DynamicRNN()
    with drnn.block():
        cur = drnn.step_input(trg_emb, seq_len=trg_len)
        st = drnn.memory(init=boot)
        ctx = _attention(st, enc, enc_proj, src_mask, hid_dim)
        inp = layers.fc([layers.concat([cur, ctx], axis=1)],
                        size=hid_dim * 3, bias_attr=False,
                        param_attr=ParamAttr(name="dec_in_w"))
        new_st, _, _ = layers.gru_unit(
            inp, st, hid_dim * 3,
            param_attr=ParamAttr(name="dec_gru_w"),
            bias_attr=ParamAttr(name="dec_gru_b"))
        drnn.update_memory(st, new_st)
        drnn.output(new_st)
    hidden = drnn()  # [B, Tt, H]
    logits = layers.fc(hidden, size=dict_size, num_flatten_dims=2,
                       param_attr=ParamAttr(name="dec_out_w"),
                       bias_attr=ParamAttr(name="dec_out_b"))
    cost = layers.softmax_with_cross_entropy(logits, label)  # [B, Tt, 1]
    trg_mask = layers.sequence_mask(trg_len, maxlen=trg_word.shape[1],
                                    dtype="float32")
    cost = layers.elementwise_mul(layers.squeeze(cost, [2]), trg_mask)
    loss = layers.reduce_sum(cost) / layers.reduce_sum(trg_mask)
    return loss


def infer_model(src_word, dict_size, emb_dim=32, hid_dim=32,
                beam_size=4, max_out_len=8, start_id=0, end_id=1):
    """Beam-search decode net sharing parameters with train_model (same
    ParamAttr names). Returns (sentence_ids [B, beam, T], sentence_scores)."""
    maxlen = src_word.shape[1]
    batch = src_word.shape[0]
    n = batch * beam_size
    enc, boot = encoder(src_word, dict_size, emb_dim, hid_dim)
    enc_proj = layers.fc(enc, size=hid_dim, num_flatten_dims=2,
                         bias_attr=False, param_attr=ParamAttr(name="att_enc_w"))
    src_mask = _mask_from(src_word._len_name, maxlen)

    # tile per beam: [B, ...] -> [B*beam, ...]
    def tile_beam(x):
        e = layers.unsqueeze(x, [1])
        tiled = layers.expand(e, [1, beam_size] + [1] * (len(x.shape) - 1))
        return layers.reshape(tiled, [n] + list(x.shape[1:]))

    enc_b = tile_beam(enc)
    enc_proj_b = tile_beam(enc_proj)
    mask_b = tile_beam(src_mask)
    state = tile_beam(boot)

    pre_ids = layers.fill_constant([n, 1], "int64", start_id)
    init_scores = np.zeros((n, 1), np.float32)
    init_scores[np.arange(n) % beam_size != 0] = -1e9  # kInitialScore trick
    pre_scores = layers.assign(init_scores)

    ids_arr = layers.create_array("int64", shape=[max_out_len, n, 1])
    scores_arr = layers.create_array("float32", shape=[max_out_len, n, 1])
    parents_arr = layers.create_array("int32", shape=[max_out_len, n])

    i = layers.fill_constant([1], "int64", 0)
    tmax = layers.fill_constant([1], "int64", max_out_len)
    cond = layers.less_than(i, tmax)
    w = layers.While(cond)
    with w.block():
        emb = layers.embedding(pre_ids, size=[dict_size, emb_dim],
                               param_attr=ParamAttr(name="trg_emb"))
        emb = layers.reshape(emb, [n, emb_dim])
        ctx = _attention(state, enc_b, enc_proj_b, mask_b, hid_dim)
        inp = layers.fc([layers.concat([emb, ctx], axis=1)],
                        size=hid_dim * 3, bias_attr=False,
                        param_attr=ParamAttr(name="dec_in_w"))
        new_st, _, _ = layers.gru_unit(
            inp, state, hid_dim * 3,
            param_attr=ParamAttr(name="dec_gru_w"),
            bias_attr=ParamAttr(name="dec_gru_b"))
        logits = layers.fc(new_st, size=dict_size,
                           param_attr=ParamAttr(name="dec_out_w"),
                           bias_attr=ParamAttr(name="dec_out_b"))
        logp = layers.log_softmax(logits)
        topk_scores, topk_idx = layers.topk(logp, k=beam_size)
        acc = layers.elementwise_add(topk_scores, pre_scores, axis=0)
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, topk_idx, acc,
            beam_size=beam_size, end_id=end_id, return_parent_idx=True)
        layers.array_write(sel_ids, i, array=ids_arr)
        layers.array_write(sel_scores, i, array=scores_arr)
        layers.array_write(parent, i, array=parents_arr)
        layers.assign(sel_ids, pre_ids)
        layers.assign(sel_scores, pre_scores)
        layers.assign(layers.gather(new_st, parent), state)
        layers.increment(i, value=1, in_place=True)
        layers.less_than(i, tmax, cond=cond)

    return layers.beam_search_decode(
        ids_arr, scores_arr, beam_size=beam_size, end_id=end_id,
        parents=parents_arr)
