"""LeNet-5 (BASELINE config 1 / reference benchmark/fluid/models/mnist.py
cnn_model structure — conv-pool ×2 + fc stack)."""

from .. import layers


def lenet5(img, label, class_num=10):
    conv1 = layers.conv2d(img, num_filters=6, filter_size=5, padding=2, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = layers.fc(pool2, size=120, act="relu")
    fc2 = layers.fc(fc1, size=84, act="relu")
    logits = layers.fc(fc2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits
