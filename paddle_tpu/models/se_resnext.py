"""SE-ResNeXt (reference benchmark/fluid + unittests/dist_se_resnext.py /
test_parallel_executor_seresnext.py — the heavier conv model of the
reference's PE-convergence and distributed test suites).

Grouped 3x3 convolutions ride XLA's feature_group_count (MXU-friendly); the
squeeze-and-excitation block is two tiny fcs around a global pool — left to
XLA fusion rather than hand-fused."""

from .. import layers
from ..layers import nn

__all__ = ["se_resnext50", "SE_ResNeXt"]


def _conv_bn(input, num_filters, filter_size, stride=1, groups=1, act="relu"):
    conv = layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        bias_attr=False,
    )
    return layers.batch_norm(input=conv, act=act)


def _squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input=input, pool_type="avg", global_pooling=True)
    pool = layers.reshape(pool, [0, num_channels])
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio, act="relu")
    excitation = layers.fc(input=squeeze, size=num_channels, act="sigmoid")
    excitation = layers.reshape(excitation, [0, num_channels, 1, 1])
    return input * excitation


def _bottleneck(input, num_filters, stride, cardinality=32, reduction_ratio=16):
    conv0 = _conv_bn(input, num_filters, 1)
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride, groups=cardinality)
    conv2 = _conv_bn(conv1, num_filters * 2, 1, act=None)
    scaled = _squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    if input.shape[1] != num_filters * 2 or stride != 1:
        shortcut = _conv_bn(input, num_filters * 2, 1, stride=stride, act=None)
    else:
        shortcut = input
    return layers.relu(scaled + shortcut)


class SE_ResNeXt:
    def __init__(self, layers_num=50, depth_override=None, filters_override=None):
        if layers_num != 50:
            raise ValueError("only the 50-layer config is provided (like the dist test)")
        # overrides give tests a structurally-identical but tiny instance
        self.depth = depth_override or [3, 4, 6, 3]
        self.num_filters = filters_override or [128, 256, 512, 1024]
        self.cardinality = 32

    def net(self, input, class_dim=1000):
        conv = _conv_bn(input, 64, 7, stride=2)
        conv = layers.pool2d(
            input=conv, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max"
        )
        for block, depth in enumerate(self.depth):
            for i in range(depth):
                conv = _bottleneck(
                    conv,
                    self.num_filters[block],
                    stride=2 if i == 0 and block != 0 else 1,
                    cardinality=self.cardinality,
                )
        pool = layers.pool2d(input=conv, pool_type="avg", global_pooling=True)
        pool = layers.reshape(pool, [0, pool.shape[1]])
        drop = layers.dropout(x=pool, dropout_prob=0.2)
        return layers.fc(input=drop, size=class_dim, act="softmax")


def se_resnext50(img, label, class_dim=1000, depth_override=None, filters_override=None):
    out = SE_ResNeXt(50, depth_override, filters_override).net(img, class_dim)
    cost = layers.cross_entropy(input=out, label=label)
    loss = layers.mean(x=cost)
    acc = layers.accuracy(input=out, label=label)
    return loss, acc, out
