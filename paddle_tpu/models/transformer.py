"""Transformer NMT (BASELINE config 3; structural parity with the reference's
fluid Transformer — python/paddle/fluid/tests/unittests/dist_transformer.py /
benchmark model: multi-head attention + FFN encoder/decoder stacks, sinusoid
position encoding, label smoothing, attention-bias tensors fed from the data
pipeline exactly as the reference does).

Everything is built from registered ops (mul/matmul/softmax/layer_norm/
dropout/...) so the whole training step compiles into one XLA module; the
batched QK^T / PV matmuls land on the MXU."""

import warnings

import numpy as np

from .. import layers
from ..initializer import NumpyArrayInitializer
from ..param_attr import ParamAttr


def position_encoding_init(n_position, d_model):
    """Sinusoid table (reference dist_transformer.py position_encoding_init)."""
    pos = np.arange(n_position)[:, None].astype("float64")
    dim = np.arange(d_model)[None, :].astype("float64")
    angle = pos / np.power(10000, 2 * (dim // 2) / d_model)
    table = np.zeros((n_position, d_model))
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table.astype("float32")


def multi_head_attention(
    queries, keys, values, attn_bias, d_key, d_value, d_model, n_head, dropout_rate,
    use_flash=False, causal=False,
):
    """With use_flash=True and no additive bias the score→softmax→context
    chain is emitted as ONE flash_attention op — the Pallas blockwise kernel
    (ops/pallas_kernels.py), ~2x faster than the dense chain at t=4096 on
    TPU and O(t) in attention memory. `causal` replaces a triangular
    attn_bias; it is honored on the dense path too."""
    q = layers.fc(queries, size=d_key * n_head, num_flatten_dims=2, bias_attr=False)
    k = layers.fc(keys, size=d_key * n_head, num_flatten_dims=2, bias_attr=False)
    v = layers.fc(values, size=d_value * n_head, num_flatten_dims=2, bias_attr=False)

    def split_heads(x, d):
        b_t = x.shape
        reshaped = layers.reshape(x, [0, 0, n_head, d])
        return layers.transpose(reshaped, [0, 2, 1, 3])  # (b, n, t, d)

    q = split_heads(q, d_key)
    k = split_heads(k, d_key)
    v = split_heads(v, d_value)

    if use_flash and attn_bias is None:
        # attention-weight dropout has no home inside the fused kernel; it is
        # skipped here like every production flash-attention integration
        ctx = layers.flash_attention(q, k, v, causal=causal, sm_scale=d_key ** -0.5)
    else:
        scores = layers.matmul(q, k, transpose_y=True, alpha=d_key ** -0.5)
        if attn_bias is not None:
            scores = layers.elementwise_add(scores, attn_bias)
        if causal:
            # the dense path must honor causal too, or a fallback would
            # silently leak future positions
            t_q, t_k = scores.shape[-2], scores.shape[-1]
            tri = np.triu(np.full((t_q, t_k), -1e9, "float32"), k=1 + t_k - t_q)
            causal_bias = layers.assign(tri)
            scores = layers.elementwise_add(scores, causal_bias)
        weights = layers.softmax(scores)
        if dropout_rate:
            weights = layers.dropout(
                weights, dropout_prob=dropout_rate, dropout_implementation="upscale_in_train"
            )
        ctx = layers.matmul(weights, v)  # (b, n, tq, dv)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, d_value * n_head])
    return layers.fc(ctx, size=d_model, num_flatten_dims=2, bias_attr=False)


def positionwise_ffn(x, d_inner, d_model, dropout_rate):
    hidden = layers.fc(x, size=d_inner, num_flatten_dims=2, act="relu")
    if dropout_rate:
        hidden = layers.dropout(
            hidden, dropout_prob=dropout_rate, dropout_implementation="upscale_in_train"
        )
    return layers.fc(hidden, size=d_model, num_flatten_dims=2)


def pre_post_process(prev, out, cmd, dropout_rate):
    """reference post-process 'da n': dropout, residual add, layer_norm"""
    for c in cmd:
        if c == "d" and dropout_rate:
            out = layers.dropout(
                out, dropout_prob=dropout_rate, dropout_implementation="upscale_in_train"
            )
        elif c == "a" and prev is not None:
            out = layers.elementwise_add(out, prev)
        elif c == "n":
            out = layers.layer_norm(out, begin_norm_axis=len(out.shape) - 1)
    return out


def encoder_layer(x, attn_bias, cfg):
    attn = multi_head_attention(
        x, x, x, attn_bias, cfg["d_key"], cfg["d_value"], cfg["d_model"],
        cfg["n_head"], cfg["dropout"],
        use_flash=cfg.get("use_flash", False),
    )
    attn = pre_post_process(x, attn, "dan", cfg["dropout"])
    ffn = positionwise_ffn(attn, cfg["d_inner"], cfg["d_model"], cfg["dropout"])
    return pre_post_process(attn, ffn, "dan", cfg["dropout"])


def decoder_layer(x, enc_out, slf_bias, cross_bias, cfg):
    # Under use_flash the decoder self-attention uses the kernel's causal
    # mask instead of the triangular bias tensor. The kernel carries no
    # key-padding mask, so this is only valid when every sequence in the
    # batch is full-length. cfg["padded"] is tri-state: True keeps the dense
    # bias-masked path for decoder self-attention; False asserts batches are
    # unpadded (flash, no warning); None (unspecified) uses flash but warns
    # so callers who never considered padding find out.
    use_flash_slf = cfg.get("use_flash", False)
    if use_flash_slf:
        padded = cfg.get("padded")
        if padded:
            use_flash_slf = False
            if slf_bias is None:
                # The dense fallback has no implicit causal mask — causality
                # comes entirely from the caller's bias tensor. Flash callers
                # conventionally pass slf_bias=None, which here would silently
                # train with future-token leakage.
                raise ValueError(
                    "transformer decoder with use_flash and padded=True takes "
                    "the dense masked path, which relies on the caller-supplied "
                    "trg_slf_attn_bias for causality — got None. Pass a causal "
                    "(+pad) bias tensor, or padded=False for the flash causal "
                    "kernel on unpadded batches."
                )
        else:
            if padded is None:
                warnings.warn(
                    "transformer decoder self-attention with use_flash drops "
                    "the attention-bias tensor and applies only a causal "
                    "mask; pad positions would be attended. Pass padded=True "
                    "for the dense masked path, or padded=False to assert "
                    "batches are unpadded and silence this warning.",
                    stacklevel=2,
                )
            slf_bias = None
    slf = multi_head_attention(
        x, x, x, slf_bias, cfg["d_key"], cfg["d_value"], cfg["d_model"],
        cfg["n_head"], cfg["dropout"],
        use_flash=use_flash_slf,
        causal=use_flash_slf,
    )
    slf = pre_post_process(x, slf, "dan", cfg["dropout"])
    # cross-attention is never causal; flash applies whenever no additive
    # bias is supplied (multi_head_attention falls back to the dense masked
    # chain when cross_bias is present — same padding contract as encoder
    # self-attention)
    cross = multi_head_attention(
        slf, enc_out, enc_out, cross_bias, cfg["d_key"], cfg["d_value"],
        cfg["d_model"], cfg["n_head"], cfg["dropout"],
        use_flash=cfg.get("use_flash", False),
    )
    cross = pre_post_process(slf, cross, "dan", cfg["dropout"])
    ffn = positionwise_ffn(cross, cfg["d_inner"], cfg["d_model"], cfg["dropout"])
    return pre_post_process(cross, ffn, "dan", cfg["dropout"])


def embed(word, pos, vocab_size, cfg, name):
    w_emb = layers.embedding(
        word,
        size=[vocab_size, cfg["d_model"]],
        param_attr=ParamAttr(name=name + "_word_emb"),
    )
    w_emb = layers.scale(w_emb, scale=cfg["d_model"] ** 0.5)
    p_emb = layers.embedding(
        pos,
        size=[cfg["max_length"], cfg["d_model"]],
        param_attr=ParamAttr(
            name=name + "_pos_emb",
            trainable=False,
            initializer=NumpyArrayInitializer(
                position_encoding_init(cfg["max_length"], cfg["d_model"])
            ),
        ),
    )
    out = layers.elementwise_add(w_emb, p_emb)
    if cfg["dropout"]:
        out = layers.dropout(
            out, dropout_prob=cfg["dropout"], dropout_implementation="upscale_in_train"
        )
    return out


def transformer(
    src_word,
    src_pos,
    trg_word,
    trg_pos,
    src_slf_attn_bias,
    trg_slf_attn_bias,
    trg_src_attn_bias,
    label,
    label_weight,
    src_vocab_size=1000,
    trg_vocab_size=1000,
    n_layer=2,
    n_head=4,
    d_model=64,
    d_inner=128,
    d_key=16,
    d_value=16,
    dropout=0.1,
    max_length=64,
    label_smooth_eps=0.1,
    use_flash=False,
    padded=None,
):
    # padded (tri-state, only meaningful under use_flash): True = batches may
    # contain pad positions, decoder self-attention keeps the dense
    # bias-masked path (the flash kernel carries no key-padding mask);
    # False = caller asserts batches are unpadded, flash runs silently;
    # None = flash runs but decoder_layer warns once
    cfg = dict(
        d_model=d_model, d_inner=d_inner, d_key=d_key, d_value=d_value,
        n_head=n_head, dropout=dropout, max_length=max_length,
        use_flash=use_flash, padded=padded,
    )
    enc = embed(src_word, src_pos, src_vocab_size, cfg, "src")
    for _ in range(n_layer):
        enc = encoder_layer(enc, src_slf_attn_bias, cfg)

    dec = embed(trg_word, trg_pos, trg_vocab_size, cfg, "trg")
    for _ in range(n_layer):
        dec = decoder_layer(dec, enc, trg_slf_attn_bias, trg_src_attn_bias, cfg)

    logits = layers.fc(dec, size=trg_vocab_size, num_flatten_dims=2, bias_attr=False)
    # label smoothing (reference: label_smooth(one_hot) + soft_label CE) via
    # the fused smooth_eps CE — same math, no [N, V] one-hot materialized
    # (that tensor dominated loss-path memory at real vocab sizes)
    flat_logits = layers.reshape(logits, [-1, trg_vocab_size])
    flat_label = layers.reshape(label, [-1, 1])
    ce = layers.softmax_with_cross_entropy(
        flat_logits, flat_label, smooth_eps=label_smooth_eps
    )
    w = layers.reshape(label_weight, [-1, 1])
    weighted = layers.elementwise_mul(ce, w)
    loss = layers.elementwise_div(
        layers.reduce_sum(weighted), layers.reduce_sum(w)
    )
    return loss, logits


def make_attn_bias(lens, maxlen, n_head, causal=False, q_maxlen=None):
    """Host-side bias construction, as the reference feeds biases from its
    data pipeline (dist_transformer.py prepare_batch_input). `lens`/`maxlen`
    describe the KEY side; `q_maxlen` the query side for cross-attention
    (defaults to maxlen for self-attention). Returns (b, n_head, q, k)."""
    b = len(lens)
    q_maxlen = q_maxlen if q_maxlen is not None else maxlen
    mask = np.zeros((b, 1, 1, maxlen), dtype="float32")
    for i, l in enumerate(lens):
        mask[i, 0, 0, l:] = -1e9
    bias = np.tile(mask, (1, n_head, q_maxlen, 1))
    if causal:
        if q_maxlen != maxlen:
            raise ValueError("causal bias requires q_maxlen == maxlen")
        tri = np.triu(np.full((maxlen, maxlen), -1e9, dtype="float32"), k=1)
        bias = bias + tri[None, None, :, :]
    return bias


def build_tiny_flash_transformer(t=16, vocab=50, feed_prefix=""):
    """Build a minimal use_flash=True transformer program on the current
    program pair — shared by the driver entry (__graft_entry__.entry) and
    tests/test_pallas_kernels.py so the flash build recipe lives in one
    place. Returns (feeds dict name->Variable, loss Variable)."""
    from .. import layers

    p = feed_prefix
    feeds = {}
    for name, shape, dtype in [
        (p + "src_word", [t], "int64"),
        (p + "src_pos", [t], "int64"),
        (p + "trg_word", [t], "int64"),
        (p + "trg_pos", [t], "int64"),
        (p + "label", [t], "int64"),
        (p + "label_weight", [t, 1], "float32"),
    ]:
        feeds[name] = layers.data(name=name, shape=shape, dtype=dtype)
    loss, _logits = transformer(
        feeds[p + "src_word"], feeds[p + "src_pos"], feeds[p + "trg_word"],
        feeds[p + "trg_pos"], None, None, None,
        feeds[p + "label"], feeds[p + "label_weight"],
        src_vocab_size=vocab, trg_vocab_size=vocab,
        n_layer=1, n_head=2, d_model=16, d_inner=32, d_key=8, d_value=8,
        dropout=0.0, max_length=t + 1, use_flash=True, padded=False,
    )
    return feeds, loss


def tiny_flash_transformer_feed(b, t=16, vocab=50, feed_prefix="", seed=5):
    """Matching numpy feed dict for build_tiny_flash_transformer."""
    p = feed_prefix
    rng = np.random.RandomState(seed)
    pos = np.tile(np.arange(t), (b, 1)).astype("int64")
    return {
        p + "src_word": rng.randint(0, vocab, (b, t)).astype("int64"),
        p + "src_pos": pos,
        p + "trg_word": rng.randint(0, vocab, (b, t)).astype("int64"),
        p + "trg_pos": pos.copy(),
        p + "label": rng.randint(0, vocab, (b, t)).astype("int64"),
        p + "label_weight": np.ones((b, t, 1), "float32"),
    }
