"""Model zoo built purely from fluid-style layers — the acceptance configs of
BASELINE.json (MNIST LeNet, ResNet-50, VGG, Transformer NMT, DeepFM CTR,
stacked-LSTM LM), mirroring reference benchmark/fluid/models/."""

from . import alexnet, googlenet, gpt_decoder, lenet, resnet, se_resnext, vgg
from .gpt_decoder import GPTDecoder
from .lenet import lenet5
from .resnet import resnet50, resnet_cifar10
from .alexnet import alexnet as alexnet_model
from .googlenet import googlenet as googlenet_model
from .se_resnext import se_resnext50
from .vgg import vgg16
