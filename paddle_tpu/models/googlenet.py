"""GoogLeNet / Inception-v1 (reference benchmark/README.md:45-51 speed-table
model). Inception blocks are four parallel conv towers concatenated on
channels — pure XLA fusion fodder; the two auxiliary classifiers weigh into
the training loss like the paper (0.3 each)."""

from .. import layers

__all__ = ["googlenet"]


def _conv(x, num_filters, filter_size, stride=1, padding=0):
    return layers.conv2d(
        input=x,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act="relu",
    )


def _inception(x, c1, c3r, c3, c5r, c5, proj):
    t1 = _conv(x, c1, 1)
    t2 = _conv(_conv(x, c3r, 1), c3, 3, padding=1)
    t3 = _conv(_conv(x, c5r, 1), c5, 5, padding=2)
    t4 = _conv(
        layers.pool2d(input=x, pool_size=3, pool_stride=1, pool_padding=1,
                      pool_type="max"),
        proj,
        1,
    )
    return layers.concat([t1, t2, t3, t4], axis=1)


def _aux_head(x, class_dim):
    pool = layers.pool2d(input=x, pool_size=5, pool_stride=3, pool_type="avg")
    conv = _conv(pool, 128, 1)
    flat = layers.reshape(conv, [0, -1])
    fc = layers.fc(input=flat, size=1024, act="relu")
    drop = layers.dropout(fc, 0.7)
    return layers.fc(input=drop, size=class_dim, act="softmax")


def googlenet(img, label, class_dim=1000, with_aux_heads=True):
    c1 = _conv(img, 64, 7, stride=2, padding=3)
    p1 = layers.pool2d(input=c1, pool_size=3, pool_stride=2, pool_type="max")
    c2 = _conv(_conv(p1, 64, 1), 192, 3, padding=1)
    p2 = layers.pool2d(input=c2, pool_size=3, pool_stride=2, pool_type="max")

    i3a = _inception(p2, 64, 96, 128, 16, 32, 32)
    i3b = _inception(i3a, 128, 128, 192, 32, 96, 64)
    p3 = layers.pool2d(input=i3b, pool_size=3, pool_stride=2, pool_type="max")

    i4a = _inception(p3, 192, 96, 208, 16, 48, 64)
    i4b = _inception(i4a, 160, 112, 224, 24, 64, 64)
    i4c = _inception(i4b, 128, 128, 256, 24, 64, 64)
    i4d = _inception(i4c, 112, 144, 288, 32, 64, 64)
    i4e = _inception(i4d, 256, 160, 320, 32, 128, 128)
    p4 = layers.pool2d(input=i4e, pool_size=3, pool_stride=2, pool_type="max")

    i5a = _inception(p4, 256, 160, 320, 32, 128, 128)
    i5b = _inception(i5a, 384, 192, 384, 48, 128, 128)
    pool = layers.pool2d(input=i5b, pool_type="avg", global_pooling=True)
    flat = layers.reshape(pool, [0, -1])
    drop = layers.dropout(flat, 0.4)
    out = layers.fc(input=drop, size=class_dim, act="softmax")

    loss = layers.mean(layers.cross_entropy(input=out, label=label))
    if with_aux_heads:
        aux1 = _aux_head(i4a, class_dim)
        aux2 = _aux_head(i4d, class_dim)
        loss1 = layers.mean(layers.cross_entropy(input=aux1, label=label))
        loss2 = layers.mean(layers.cross_entropy(input=aux2, label=label))
        loss = loss + 0.3 * loss1 + 0.3 * loss2
    acc = layers.accuracy(input=out, label=label)
    return loss, acc, out
