"""DeepFM CTR model (BASELINE config 4; the reference era's CTR tier —
dist_ctr.py / deep-and-wide models built on sparse lookup_table + logloss +
AUC). FM second-order term uses the sum-square identity
0.5 * ((Σv)² − Σv²) so everything is one dense XLA computation.

Embedding routing (the PR 8 sparse engine, paddle_tpu/embedding/):
- `is_sparse=True` makes both tables' gradients SelectedRows pairs with
  per-row optimizer updates — cost O(batch·fields·dim), not O(num_features);
- `use_distributed=True` row-shards both tables over the mesh `axis_name`
  (EmbeddingEngine; requires num_features divisible by the axis extent);
- `hash_size=N` routes raw ids through the PR 3 `hash` op (XXH32 mod N) so
  an unbounded id space feeds a fixed-size table, and the tables are sized
  by hash_size instead of num_features."""

from .. import layers
from ..param_attr import ParamAttr


def deepfm(
    feat_ids,
    label,
    num_features=10000,
    num_fields=10,
    embedding_size=8,
    layer_sizes=(64, 32),
    is_sparse=False,
    use_distributed=False,
    axis_name="ep",
    hash_size=None,
):
    """feat_ids: (b, num_fields, 1) int ids into a shared feature space."""
    if hash_size is not None:
        # (b*f, num_hash=1, 1) bucket ids -> back to (b, f, 1)
        flat = layers.reshape(feat_ids, [-1, 1])
        hashed = layers.hash(flat, hash_size=hash_size, num_hash=1)
        feat_ids = layers.reshape(hashed, [-1, num_fields, 1])
        num_features = hash_size

    def table(size, name):
        if use_distributed:
            return layers.distributed_embedding(
                feat_ids,
                size=size,
                param_attr=ParamAttr(name=name),
                axis_name=axis_name,
                is_sparse=is_sparse,
            )
        return layers.embedding(
            feat_ids,
            size=size,
            is_sparse=is_sparse,
            param_attr=ParamAttr(name=name),
        )

    # first-order term: per-feature scalar weights
    first_emb = table([num_features, 1], "fm_first")  # (b, f, 1)
    y_first = layers.reduce_sum(layers.reshape(first_emb, [0, num_fields]), dim=[1], keep_dim=True)

    # second-order term via sum-square trick
    emb = table([num_features, embedding_size], "fm_emb")  # (b, f, k)
    summed = layers.reduce_sum(emb, dim=[1])  # (b, k)
    sum_sq = layers.square(summed)
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    y_second = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=[1], keep_dim=True),
        scale=0.5,
    )

    # deep tower
    deep = layers.reshape(emb, [0, num_fields * embedding_size])
    for width in layer_sizes:
        deep = layers.fc(deep, size=width, act="relu")
    y_deep = layers.fc(deep, size=1)

    logit = layers.elementwise_add(
        layers.elementwise_add(y_first, y_second), y_deep
    )
    pred = layers.sigmoid(logit)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    return loss, pred, logit
