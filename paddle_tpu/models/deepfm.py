"""DeepFM CTR model (BASELINE config 4; the reference era's CTR tier —
dist_ctr.py / deep-and-wide models built on sparse lookup_table + logloss +
AUC). FM second-order term uses the sum-square identity
0.5 * ((Σv)² − Σv²) so everything is one dense XLA computation; embedding
gradients are fused scatter-adds (SelectedRows' TPU-native equivalent —
SURVEY.md §7.7), and sharded tables come from the parallel embedding path."""

from .. import layers
from ..param_attr import ParamAttr


def deepfm(
    feat_ids,
    label,
    num_features=10000,
    num_fields=10,
    embedding_size=8,
    layer_sizes=(64, 32),
):
    """feat_ids: (b, num_fields, 1) int ids into a shared feature space."""
    # first-order term: per-feature scalar weights
    first_emb = layers.embedding(
        feat_ids,
        size=[num_features, 1],
        param_attr=ParamAttr(name="fm_first"),
    )  # (b, f, 1)
    y_first = layers.reduce_sum(layers.reshape(first_emb, [0, num_fields]), dim=[1], keep_dim=True)

    # second-order term via sum-square trick
    emb = layers.embedding(
        feat_ids,
        size=[num_features, embedding_size],
        param_attr=ParamAttr(name="fm_emb"),
    )  # (b, f, k)
    summed = layers.reduce_sum(emb, dim=[1])  # (b, k)
    sum_sq = layers.square(summed)
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    y_second = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=[1], keep_dim=True),
        scale=0.5,
    )

    # deep tower
    deep = layers.reshape(emb, [0, num_fields * embedding_size])
    for width in layer_sizes:
        deep = layers.fc(deep, size=width, act="relu")
    y_deep = layers.fc(deep, size=1)

    logit = layers.elementwise_add(
        layers.elementwise_add(y_first, y_second), y_deep
    )
    pred = layers.sigmoid(logit)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    return loss, pred, logit
