"""Stacked dynamic-LSTM text model (BASELINE config 5; structural parity with
reference benchmark/fluid/models/stacked_dynamic_lstm.py: embedding → per
layer [fc(4h) → dynamic_lstm] → max-pool both streams → fc softmax)."""

from .. import layers


def stacked_lstm_net(
    words, label, dict_dim, emb_dim=128, hid_dim=128, stacked_num=3, class_num=2
):
    emb = layers.embedding(words, size=[dict_dim, emb_dim])
    fc1 = layers.fc(emb, size=hid_dim * 4)
    lstm1, cell1 = layers.dynamic_lstm(fc1, size=hid_dim * 4)
    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = layers.fc(inputs, size=hid_dim * 4)
        lstm, cell = layers.dynamic_lstm(fc, size=hid_dim * 4)
        inputs = [fc, lstm]
    fc_last = layers.sequence_pool(inputs[0], pool_type="max")
    lstm_last = layers.sequence_pool(inputs[1], pool_type="max")
    logits = layers.fc([fc_last, lstm_last], size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits
