"""Decoder-only transformer for autoregressive generation serving.

A deliberately small GPT-style zoo entry whose point is not the model but
the *three program families* it can emit over one shared parameter set
(explicit ``ParamAttr`` names, the machine_translation train/infer sharing
pattern):

  * ``build_forward``  — whole-sequence causal logits ``[b, t, vocab]``.
    Used for training, parity tests, and as the naive
    whole-sequence-per-request serving ablation in ``bench.py generation``.
  * ``build_prefill``  — one prompt *chunk* of bucketed static length ``t``
    (batch 1) starting at an arbitrary position: K/V of every chunk row
    scattered into the paged pool through the slot's page list, then paged
    attention back over the pool (causal by position), logits of one
    selected row. A chunk at start 0 covering the whole prompt is ordinary
    whole-prompt prefill; long prompts run several chunk calls interleaved
    with decode steps, and prefix-cache hits skip the leading chunks
    entirely (serving/generation.py).
  * ``build_decode``   — one token for every slot ``[slots]``: K/V written
    at ``positions`` through per-slot block tables, ``paged_attention``
    over the pool, logits ``[slots, vocab]``.

All three lower through ``executor.aot_serve_lowering``; the
``GenerationEngine`` (serving/generation.py) compiles prefill buckets and
one decode shape ahead of time so the serving hot loop never retraces. The
same protocol (``build_prefill`` / ``build_decode`` / ``kv_pool_names`` /
``ensure_params``) is the hook point for other decode-loop models — e.g.
wrapping the NMT infer path's decoder — to ride the engine.

Prefill writes K/V for *padded* positions too (the program is static over
the chunk length): positions beyond the slot's allocated pages (or past
the table's capacity) land in the pool's scratch page 0, and positions
between the prompt length and the chunk end inside allocated pages are
overwritten by the decode step that claims that position before any
attention read reaches them — see docs/serving.md for the lifecycle
argument.
"""

import numpy as np

from .. import framework, unique_name
from .. import layers
from ..executor import Executor
from ..param_attr import ParamAttr

__all__ = ["GPTDecoder"]


class GPTDecoder:
    def __init__(
        self,
        vocab_size=128,
        n_layer=2,
        n_head=2,
        d_model=32,
        d_inner=64,
        max_context=64,
        eos_id=1,
        prefix="gptd",
        kv_dtype="float32",
    ):
        if d_model % n_head:
            raise ValueError("d_model must divide into n_head heads")
        if kv_dtype not in ("float32", "int8"):
            raise ValueError("kv_dtype must be 'float32' or 'int8'")
        self.vocab_size = int(vocab_size)
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.d_model = int(d_model)
        self.d_head = self.d_model // self.n_head
        self.d_inner = int(d_inner)
        self.max_context = int(max_context)
        self.eos_id = int(eos_id)
        self.prefix = prefix
        # "int8": K/V pools store symmetric per-row int8 levels plus a
        # [pool_rows] f32 scale pool each (kv_scale_names) — half the HBM
        # per cached token, so the same pool budget holds ~2x the slots
        # (ops/generation_ops.py int8 pool mode)
        self.kv_dtype = kv_dtype

    # ---------------------------------------------------------------- names

    def _p(self, *parts):
        return "_".join((self.prefix,) + parts)

    def param_names(self):
        names = [self._p("tok_emb"), self._p("pos_emb")]
        for i in range(self.n_layer):
            li = "l%d" % i
            names += [self._p(li, s) for s in (
                "ln1_w", "ln1_b", "q_w", "k_w", "v_w", "o_w",
                "ln2_w", "ln2_b", "ff1_w", "ff1_b", "ff2_w", "ff2_b",
            )]
        names += [self._p("lnf_w"), self._p("lnf_b"), self._p("head_w")]
        return names

    def kv_pool_names(self):
        """[(k_pool, v_pool)] per layer; each pool row holds n_head*d_head
        features for one cached token."""
        return [
            (self._p("l%d" % i, "kv_k"), self._p("l%d" % i, "kv_v"))
            for i in range(self.n_layer)
        ]

    def kv_scale_names(self):
        """[(k_scales, v_scales)] per layer in int8 mode (each a
        [pool_rows] f32 per-row scale pool, written-state siblings of the
        level pools); [] in float32 mode."""
        if self.kv_dtype != "int8":
            return []
        return [
            (self._p("l%d" % i, "kv_ks"), self._p("l%d" % i, "kv_vs"))
            for i in range(self.n_layer)
        ]

    # ------------------------------------------------------------ submodules

    def _attr(self, i, suffix):
        return ParamAttr(name=self._p("l%d" % i, suffix))

    def _embed(self, tokens, positions):
        tok = layers.embedding(
            tokens,
            size=[self.vocab_size, self.d_model],
            param_attr=ParamAttr(name=self._p("tok_emb")),
        )
        pos = layers.embedding(
            positions,
            size=[self.max_context, self.d_model],
            param_attr=ParamAttr(name=self._p("pos_emb")),
        )
        return layers.elementwise_add(tok, pos)

    def _qkv(self, h, i, nfd):
        mk = lambda s: layers.fc(
            h, size=self.d_model, num_flatten_dims=nfd,
            param_attr=self._attr(i, s), bias_attr=False,
        )
        return mk("q_w"), mk("k_w"), mk("v_w")

    def _mlp_tail(self, x, i, nfd):
        """Residual-add of attention output is done by the caller; this is
        ln2 + ffn + residual."""
        h = layers.layer_norm(
            x, begin_norm_axis=nfd,
            param_attr=self._attr(i, "ln2_w"), bias_attr=self._attr(i, "ln2_b"),
        )
        f = layers.fc(
            h, size=self.d_inner, num_flatten_dims=nfd, act="relu",
            param_attr=self._attr(i, "ff1_w"), bias_attr=self._attr(i, "ff1_b"),
        )
        f = layers.fc(
            f, size=self.d_model, num_flatten_dims=nfd,
            param_attr=self._attr(i, "ff2_w"), bias_attr=self._attr(i, "ff2_b"),
        )
        return layers.elementwise_add(x, f)

    def _dense_block(self, x, i, t):
        """Pre-LN block over [b, t, d_model] with dense causal attention
        (the whole-sequence training/oracle form)."""
        h = layers.layer_norm(
            x, begin_norm_axis=2,
            param_attr=self._attr(i, "ln1_w"), bias_attr=self._attr(i, "ln1_b"),
        )
        q, k, v = self._qkv(h, i, nfd=2)
        split = lambda y: layers.transpose(
            layers.reshape(y, [0, 0, self.n_head, self.d_head]), [0, 2, 1, 3]
        )
        qh, kh, vh = split(q), split(k), split(v)
        scores = layers.matmul(qh, kh, transpose_y=True, alpha=self.d_head**-0.5)
        tri = layers.assign(np.triu(np.full((t, t), -1e9, "float32"), k=1))
        scores = layers.elementwise_add(scores, tri)
        ctx = layers.matmul(layers.softmax(scores), vh)
        ctx = layers.reshape(layers.transpose(ctx, [0, 2, 1, 3]), [0, 0, self.d_model])
        o = layers.fc(
            ctx, size=self.d_model, num_flatten_dims=2,
            param_attr=self._attr(i, "o_w"), bias_attr=False,
        )
        return self._mlp_tail(layers.elementwise_add(x, o), i, nfd=2)

    def _decode_block(self, x, i, pools, scales, block_table, pos, page_size):
        """Pre-LN block over [rows, d_model] — one query token per row:
        write each row's K/V into the pool, then attend through the block
        table ([rows, max_pages] for decode; [max_pages], shared by every
        row, for a prefill chunk). `scales` is the per-layer scale-pool pair
        list in int8 mode, None in float32 mode."""
        h = layers.layer_norm(
            x, begin_norm_axis=1,
            param_attr=self._attr(i, "ln1_w"), bias_attr=self._attr(i, "ln1_b"),
        )
        q, k, v = self._qkv(h, i, nfd=1)
        k_pool, v_pool = pools[i]
        k_sc, v_sc = scales[i] if scales else (None, None)
        layers.kv_cache_write(k_pool, k, block_table, pos, page_size, k_sc)
        layers.kv_cache_write(v_pool, v, block_table, pos, page_size, v_sc)
        att = layers.paged_attention(
            q, k_pool, v_pool, block_table, pos,
            n_head=self.n_head, page_size=page_size,
            k_scales=k_sc, v_scales=v_sc,
        )
        o = layers.fc(
            att, size=self.d_model, num_flatten_dims=1,
            param_attr=self._attr(i, "o_w"), bias_attr=False,
        )
        return self._mlp_tail(layers.elementwise_add(x, o), i, nfd=1)

    def _final(self, x, nfd):
        h = layers.layer_norm(
            x, begin_norm_axis=nfd,
            param_attr=ParamAttr(name=self._p("lnf_w")),
            bias_attr=ParamAttr(name=self._p("lnf_b")),
        )
        return h

    def _head(self, h, nfd):
        return layers.fc(
            h, size=self.vocab_size, num_flatten_dims=nfd,
            param_attr=ParamAttr(name=self._p("head_w")), bias_attr=False,
        )

    def _pool_vars(self, pool_rows):
        block = framework.default_main_program().global_block()
        pools = [
            tuple(
                block.create_var(
                    name=n, shape=[pool_rows, self.d_model],
                    dtype=self.kv_dtype, persistable=True,
                )
                for n in pair
            )
            for pair in self.kv_pool_names()
        ]
        scales = [
            tuple(
                block.create_var(
                    name=n, shape=[pool_rows], dtype="float32",
                    persistable=True,
                )
                for n in pair
            )
            for pair in self.kv_scale_names()
        ]
        return pools, scales or None

    # -------------------------------------------------------------- programs

    def build_forward(self, batch, t):
        """Whole-sequence causal LM: feed fwd_tokens [batch, t, 1] int64,
        fetch logits [batch, t, vocab]. The serving ablation and parity
        oracle. (Token ids carry a trailing 1 dim, the lookup_table LoD
        convention, so rank is stable for any batch/t.)"""
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), unique_name.guard(
            "%s_fw%dx%d_" % (self.prefix, batch, t)
        ):
            tokens = layers.data(
                "fwd_tokens", [batch, t, 1], append_batch_size=False, dtype="int64"
            )
            positions = layers.assign(np.arange(t, dtype="int64").reshape(1, t, 1))
            x = self._embed(tokens, positions)
            for i in range(self.n_layer):
                x = self._dense_block(x, i, t)
            logits = self._head(self._final(x, nfd=2), nfd=2)
        return main, startup, ["fwd_tokens"], [logits.name]

    def build_prefill(self, t, page_size, max_pages, pool_rows):
        """Paged chunk prefill (batch 1): feed gen_tokens [1, t, 1] int64
        (zero-padded), gen_start [1] int64 (absolute position of the
        chunk's first token), gen_last [1] int64 (in-chunk row whose logits
        to fetch), gen_pages [max_pages] int32 (the slot's page list,
        scratch-0 padded). K/V of all t chunk rows scatter into the pool at
        positions gen_start + [0, t), then every row attends the pool
        through the page list (causal by position) — so a long prompt may
        ingest in several chunk calls, each reading back the pages earlier
        chunks (or a shared prefix-cache hit) already filled. A chunk at
        gen_start 0 with t covering the whole prompt is ordinary
        whole-prompt prefill: one program family serves both. Fetch the
        gen_last row's logits [1, vocab].

        Padded tail rows past the context bound are harmless by
        construction: their kv_cache_write positions are routed to the
        scratch page by the op's capacity guard, and the position-embedding
        lookup is clamped (their logits are never fetched)."""
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), unique_name.guard(
            "%s_pf%d_" % (self.prefix, t)
        ):
            tokens = layers.data(
                "gen_tokens", [1, t, 1], append_batch_size=False, dtype="int64"
            )
            start = layers.data(
                "gen_start", [1], append_batch_size=False, dtype="int64"
            )
            last = layers.data(
                "gen_last", [1], append_batch_size=False, dtype="int64"
            )
            pages = layers.data(
                "gen_pages", [max_pages], append_batch_size=False, dtype="int32"
            )
            pools, scales = self._pool_vars(pool_rows)
            pos_flat = layers.elementwise_add(
                layers.assign(np.arange(t, dtype="int64")), start
            )
            emb_pos = layers.elementwise_min(
                pos_flat,
                layers.assign(np.full([1], self.max_context - 1, "int64")),
            )
            x = self._embed(tokens, layers.reshape(emb_pos, [1, t, 1]))
            x2 = layers.reshape(x, [t, self.d_model])
            for i in range(self.n_layer):
                x2 = self._decode_block(
                    x2, i, pools, scales, pages, pos_flat, page_size
                )
            h = self._final(x2, nfd=1)
            last_row = layers.gather(h, last)  # [1, d_model]
            logits = self._head(last_row, nfd=1)
        return (
            main,
            startup,
            ["gen_tokens", "gen_start", "gen_last", "gen_pages"],
            [logits.name],
        )

    def build_decode(self, slots, page_size, max_pages, pool_rows):
        """One decode step for every slot: feed dec_tokens [slots, 1] int64,
        dec_positions [slots, 1] int64, dec_block_table [slots, max_pages]
        int32; fetch logits [slots, vocab]. Idle slots carry position 0 and
        a scratch-only block table — their writes land in scratch page 0 and
        their logits are ignored by the scheduler."""
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), unique_name.guard(
            "%s_dec%d_" % (self.prefix, slots)
        ):
            tokens = layers.data(
                "dec_tokens", [slots, 1], append_batch_size=False, dtype="int64"
            )
            positions = layers.data(
                "dec_positions", [slots, 1], append_batch_size=False, dtype="int64"
            )
            block_table = layers.data(
                "dec_block_table", [slots, max_pages],
                append_batch_size=False, dtype="int32",
            )
            pools, scales = self._pool_vars(pool_rows)
            x = self._embed(tokens, positions)
            for i in range(self.n_layer):
                x = self._decode_block(
                    x, i, pools, scales, block_table, positions, page_size
                )
            logits = self._head(self._final(x, nfd=1), nfd=1)
        return (
            main,
            startup,
            ["dec_tokens", "dec_positions", "dec_block_table"],
            [logits.name],
        )

    # ---------------------------------------------------------------- params

    def ensure_params(self, scope, place=None):
        """Initialize the shared parameter set into `scope` if absent (runs
        the forward startup program once, the train/infer sharing idiom)."""
        if all(n in scope.vars for n in self.param_names()):
            return
        _, startup, _, _ = self.build_forward(1, min(8, self.max_context))
        from ..executor import scope_guard

        with scope_guard(scope):
            Executor(place).run(startup)
