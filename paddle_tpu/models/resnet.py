"""ResNet (BASELINE config 2; structural parity with reference
benchmark/fluid/models/resnet.py — conv_bn_layer / shortcut / bottleneck
blocks — written fluid-style against our layers API).

TPU notes: NCHW layout with XLA handling the layout assignment; batch_norm in
f32 accumulate; the MXU sees the convs via conv_general_dilated."""

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1, act=None):
    conv = layers.conv2d(
        input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride)
    return input


def bottleneck_block(input, num_filters, stride):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, act="relu")
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None)
    short = shortcut(input, num_filters * 4, stride)
    return layers.elementwise_add(short, conv2, act="relu")


def basic_block(input, num_filters, stride):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, act=None)
    short = shortcut(input, num_filters, stride)
    return layers.elementwise_add(short, conv1, act="relu")


def resnet50(img, label, class_num=1000):
    """ResNet-50 v1 for ImageNet-sized inputs (N,3,224,224)."""
    depth = [3, 4, 6, 3]
    num_filters = [64, 128, 256, 512]
    conv = conv_bn_layer(img, 64, 7, stride=2, act="relu")
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1)
    for block in range(len(depth)):
        for i in range(depth[block]):
            pool = bottleneck_block(
                pool, num_filters[block], stride=2 if i == 0 and block != 0 else 1
            )
    pool = layers.pool2d(pool, pool_type="avg", global_pooling=True)
    logits = layers.fc(pool, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits


def resnet_cifar10(img, label, depth=32, class_num=10):
    """ResNet for CIFAR (reference benchmark/fluid/models/resnet.py
    resnet_cifar10: 6n+2 basic blocks)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv = conv_bn_layer(img, 16, 3, act="relu")
    for filters, stride in [(16, 1), (32, 2), (64, 2)]:
        for i in range(n):
            conv = basic_block(conv, filters, stride if i == 0 else 1)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    logits = layers.fc(pool, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits
