"""VGG-16/19 (reference benchmark/fluid/models/vgg.py conv_block structure;
VGG-19 is the configuration the reference publishes train/infer baselines
for, benchmark/IntelOptimizedPaddle.md:29-37)."""

from .. import layers


def conv_block(input, num_filter, groups):
    conv = input
    for _ in range(groups):
        conv = layers.conv2d(
            conv, num_filters=num_filter, filter_size=3, padding=1, act="relu"
        )
    return layers.pool2d(conv, pool_size=2, pool_stride=2)


def _vgg(img, label, depths, class_num, dropout):
    conv = img
    for filters, groups in zip((64, 128, 256, 512, 512), depths):
        conv = conv_block(conv, filters, groups)
    fc1 = layers.fc(conv, size=4096, act="relu")
    if dropout:
        fc1 = layers.dropout(fc1, dropout_prob=0.5)
    fc2 = layers.fc(fc1, size=4096, act="relu")
    if dropout:
        fc2 = layers.dropout(fc2, dropout_prob=0.5)
    logits = layers.fc(fc2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits


def vgg19(img, label, class_num=1000, dropout=True):
    return _vgg(img, label, (2, 2, 4, 4, 4), class_num, dropout)


def vgg16(img, label, class_num=1000, dropout=True):
    conv1 = conv_block(img, 64, 2)
    conv2 = conv_block(conv1, 128, 2)
    conv3 = conv_block(conv2, 256, 3)
    conv4 = conv_block(conv3, 512, 3)
    conv5 = conv_block(conv4, 512, 3)
    fc1 = layers.fc(conv5, size=4096, act="relu")
    if dropout:
        fc1 = layers.dropout(fc1, dropout_prob=0.5)
    fc2 = layers.fc(fc1, size=4096, act="relu")
    if dropout:
        fc2 = layers.dropout(fc2, dropout_prob=0.5)
    logits = layers.fc(fc2, size=class_num)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits
