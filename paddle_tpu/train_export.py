"""Artifact-only training: export a compiled TRAIN step and run it with no
Program/frontend in the loop.

Reference analog: /root/reference/paddle/fluid/train/demo/demo_trainer.cc —
the reference ships a C++ driver that loads saved program artifacts
(startup/main ProgramDesc + persistables) and trains without the Python
frontend. The TPU-native equivalent exports the WHOLE optimizer-bearing
train step — forward, backward, and parameter update, exactly as the
Executor would jit it — as one serialized StableHLO artifact (jax.export)
together with the initial state pytree (params, optimizer accumulators,
running stats, PRNG key). `TrainStepRunner` deserializes the artifact and
loops feed -> step -> new state; the training loop touches no Program,
no ops, no layers — just arrays in, loss out, state carried.

Unlike `inference.export_compiled` (serving: fetches only, state frozen),
the train artifact returns its mutated state and threads the PRNG key, so
dropout/augmentation ops stay stochastic across artifact steps.

The artifact records the platform it was lowered for (cpu/tpu); jax.export
enforces it at call time.
"""

import os

import numpy as np

__all__ = ["export_train_step", "TrainStepRunner", "load_train_step"]


def _npz(path):
    return path if path.endswith(".npz") else path + ".npz"


def export_train_step(out_path, feed_example, fetch_list, program=None,
                      scope=None):
    """AOT-compile the training block for the example feed shapes and write
    the artifact: StableHLO blob + read-only state + mutable state + PRNG
    key. Run the startup program first (the block must create no new
    persistables — accumulators are startup-initialized).

    feed_example: dict name -> numpy array (shapes/dtypes fix the artifact).
    fetch_list: Variables or names fetched each step (e.g. the loss).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from . import framework
    from .executor import _CompiledBlock, global_scope

    program = program or framework.default_main_program()
    scope = scope or global_scope()
    block = program.global_block()
    feed = {k: np.asarray(v) for k, v in feed_example.items()}
    fetch_names = [
        f.name if isinstance(f, framework.Variable) else str(f)
        for f in fetch_list
    ]
    compiled = _CompiledBlock(
        program, block, list(feed.keys()), fetch_names, scope
    )
    if compiled.created_persistables:
        raise RuntimeError(
            "train block creates persistables %s — run the startup program "
            "before exporting" % compiled.created_persistables
        )

    def step(feeds, ro, mut, key_data):
        key = jax.random.wrap_key_data(key_data)
        fetches, new_mut, _created, new_key = compiled.fn(feeds, ro, mut, key)
        return fetches, new_mut, jax.random.key_data(new_key)

    ro = {n: jnp.asarray(scope.vars[n]) for n in compiled.ro_names}
    mut = {n: jnp.asarray(scope.vars[n]) for n in compiled.mut_names}
    key_data = jax.random.key_data(scope.rng_key)
    exported = jax_export.export(jax.jit(step, donate_argnums=(2,)))(
        {k: jnp.asarray(v) for k, v in feed.items()}, ro, mut, key_data
    )
    blob = exported.serialize()

    arrays = {
        "__stablehlo__": np.frombuffer(blob, np.uint8),
        "__feed_names__": np.array(sorted(feed.keys())),
        "__fetch_names__": np.array(fetch_names),
        "__rng__": np.asarray(key_data),
    }
    for n, v in ro.items():
        arrays["ro:" + n] = np.asarray(v)
    for n, v in mut.items():
        arrays["mut:" + n] = np.asarray(v)
    out_path = _npz(out_path)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "wb") as f:
        np.savez(f, **arrays)
    return out_path


class TrainStepRunner:
    """Program-free training loop over an export_train_step artifact (the
    demo_trainer.cc role). State (params + accumulators + PRNG) is carried
    inside the runner; run() takes a feed dict and returns the fetches."""

    def __init__(self, exported, feed_names, fetch_names, ro, mut, key_data):
        import jax

        self._call = jax.jit(exported.call, donate_argnums=(2,))
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self._ro = ro
        self._mut = mut
        self._key = key_data

    @classmethod
    def load(cls, path):
        import jax.numpy as jnp
        from jax import export as jax_export

        data = np.load(_npz(path))
        exported = jax_export.deserialize(data["__stablehlo__"].tobytes())
        return cls(
            exported,
            [str(s) for s in data["__feed_names__"]],
            [str(s) for s in data["__fetch_names__"]],
            {k[3:]: jnp.asarray(data[k]) for k in data.files
             if k.startswith("ro:")},
            {k[4:]: jnp.asarray(data[k]) for k in data.files
             if k.startswith("mut:")},
            jnp.asarray(data["__rng__"]),
        )

    def run(self, feed):
        """One training step: feed dict name -> array; returns numpy fetches
        (loss etc.). Mutated state is donated and re-carried."""
        import jax.numpy as jnp

        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError("missing feeds: %s" % missing)
        feeds = {n: jnp.asarray(feed[n]) for n in self.feed_names}
        fetches, self._mut, self._key = self._call(
            feeds, self._ro, self._mut, self._key
        )
        return [np.asarray(f) for f in fetches]

    def state(self):
        """Snapshot of the mutable state (params, accumulators) as numpy —
        feed into io-style checkpointing or back into a Scope."""
        return {n: np.asarray(v) for n, v in self._mut.items()}

    def save_state(self, path):
        path = _npz(path)
        with open(path, "wb") as f:
            np.savez(f, **self.state())
        return path

    def load_state(self, path):
        import jax.numpy as jnp

        data = np.load(_npz(path))
        if set(data.files) != set(self._mut):
            raise ValueError(
                "checkpoint does not match this artifact's state: missing %s,"
                " unexpected %s"
                % (sorted(set(self._mut) - set(data.files)),
                   sorted(set(data.files) - set(self._mut)))
            )
        for n in list(self._mut):
            self._mut[n] = jnp.asarray(data[n])


def load_train_step(path):
    return TrainStepRunner.load(path)
