"""Runtime flags (reference gflags tier, SURVEY.md §5.6: ~45 DEFINE_* knobs
surfaced to Python via core.init_gflags and FLAGS_* env vars,
fluid/__init__.py:125-150).

TPU-first mapping: most reference flags governed machinery XLA now owns
(memory fractions → XLA allocator; cudnn knobs → compiler choices), so the
surviving knobs are debug/determinism switches. Flags initialize from
FLAGS_* environment variables exactly like the reference, and can be set
programmatically with set_flags (the modern fluid API shape).

Honored flags:
- check_nan_inf: executor scans every fetch/updated state for NaN/Inf after
  each run and raises (reference operator.cc:778 FLAGS_check_nan_inf).
- benchmark: executor blocks until device work completes each run, so host
  timing brackets real step time (reference operator.cc:769 FLAGS_benchmark).
- rpc_max_retry / rpc_deadline: socket RPC reconnect-retry count and call
  timeout (reference grpc_client.cc FLAGS_max_retry / FLAGS_rpc_deadline).
- rpc_op_deadline: per-operation connect/read deadline (seconds) inside one
  RPC attempt — a hung peer surfaces as a typed resilience.DeadlineExceeded
  instead of blocking forever; rpc_deadline remains the OVERALL retry budget.
- resilience_nan_guard: executor skips a training step whose fetches/updated
  state went NaN/Inf — restores the pre-step state, decays the loss scale /
  learning rate by resilience_lr_decay, and counts the event in
  resilience.health instead of crashing (docs/resilience.md).
- resilience_lr_decay: multiplicative decay the NaN guard applies to
  loss-scale / learning-rate vars on each skipped step.
- dist_init_max_retry: retry attempts for the multi-host rendezvous
  (parallel/multihost.py init_distributed) before surfacing the error.
- profile_ops: while the profiler is on, run blocks op-by-op EAGERLY with a
  device sync per op, so the profiler table attributes time per op type —
  the reference's per-op RecordEvent tables (operator.cc:157). Slower and
  unfused by construction; a diagnosis mode, never a training mode.
- telemetry_dir: when set, the observability layer exports per-step
  telemetry (JSONL event shards + a Prometheus scrape file) into this
  directory — docs/observability.md; empty (default) disables export.
- telemetry_interval_steps: steps between snapshot records / Prometheus
  rewrites / the rank-0 shard merge (observability/export.py).
- telemetry_log_every: > 0 prints one structured health line to stderr
  every N recorded steps (step ms, steps/s, loss if fetched, health counter
  deltas) — the "is it alive" signal for long runs; 0 (default) disables.
- tensor_stats: glob over op display names ("<type>:<first output>"), op
  types, or output var names. Matching ops get on-device output statistics
  (mean/std/absmax/nonfinite count) computed INSIDE the compiled step and
  streamed through the telemetry path with one host sync per run
  (observability/opprof.py, docs/observability.md); "" (default) disables
  and compiles the unmodified step.
- nan_provenance: when the resilience NaN guard or FLAGS_check_nan_inf
  trips, re-run that step's feed through an op-by-op interpreter walk to
  localize the FIRST op emitting non-finite output, and write a provenance
  record (op type/name, input stats, attrs, step) to the telemetry dir plus
  a health/nan_provenance counter. Off (default): failures name only the
  variable, as before.
- trace_dir: when set, the distributed request tracer
  (observability/tracing.py) exports kept trace segments as per-process
  rotation-safe JSONL shards ``trace-host<k>-p<pid>.jsonl`` into this
  directory — the fleet's per-request causality record (router attempt/
  hedge spans, replica server spans, batcher/scheduler lifecycles, engine
  execute spans). "" (default) disables shard export; span creation stays
  on only if flightrec_dir needs the ring. With both unset the hot path
  allocates nothing (NULL_SPAN).
- trace_sample: fraction of OK traces kept by tail sampling, decided by a
  deterministic hash of the trace id so every process keeps the same
  traces. Error, slow and hedged traces are ALWAYS kept. 1.0 (default)
  keeps everything.
- trace_slow_ms: a trace segment containing any span at least this slow is
  exempt from sampling (always kept).
- trace_ring: per-process ring capacity (ended spans, sampled or not) —
  the flight recorder's lookback window.
- flightrec_dir: when set, anomaly triggers (replica 5xx, breaker
  transition, NaN-guard trip, watchdog stall, staleness throttle) dump an
  atomic flight-recorder bundle directory (spans.jsonl + metrics.json +
  event.json + env.json) here — observability/flightrec.py,
  docs/observability.md. "" (default) disables; trigger() is then a no-op.
- flightrec_max_bundles: newest bundles kept on disk (oldest pruned).
- flightrec_min_interval_s: per-reason rate limit between bundles.
- serving_cache_dir: default persistent compile-cache directory for the
  serving runtime (serving/compile_cache.py): ServingEngine instances built
  without an explicit cache_dir store/load serialized jax.export artifacts
  here, and JAX's persistent XLA-executable cache is pointed at its xla/
  subdir — a warm replica cold-starts without tracing or compiling
  (docs/serving.md); "" (default) disables the persistent layer (variants
  still cache in-process).
- paged_flash: dispatch tier for the paged flash-attention serving kernel
  (ops/pallas_kernels.paged_flash_attention, the decode/chunked-prefill
  fast path behind the paged_attention lowering). "auto" (default) takes
  the Pallas kernel on a real TPU and the dense flat-gather reference
  elsewhere (an interpreted kernel in the decode hot loop is slower than
  dense XLA on the CPU test mesh); "on" forces the kernel everywhere —
  interpret mode off-TPU, how the hermetic parity tests pin it; "off"
  forces the dense reference. paged_flash_path_taken mirrors the decision.
- gemm_double_buffer: dispatch tier for the manual double-buffered k-loop
  DMA variant of the fused GEMM kernel (overlaps the HBM→VMEM tile fetch
  of iteration k+1 with the MXU contraction of iteration k). Same
  "auto"/"on"/"off" semantics as paged_flash; outputs are bit-identical
  to the grid-pipelined kernel either way (same accumulation order).
- quantized_gemm: dispatch tier for the quantized GEMM tile paths
  (ops/pallas_kernels.quant_gemm_bias_act — int8×int8→i32 and
  fp8(e4m3)×fp8→f32 with the dequantize multiply folded into the GEMM
  epilogue). Same "auto"/"on"/"off" semantics as paged_flash; the dense
  fallback keeps the same wide-accumulate/round-once numerics either way.
  quant_gemm_path_taken mirrors the decision.
- fp8_matmul: when True, the training matmul/mul lowerings cast floating
  operands to float8_e4m3fn and contract with f32 accumulation
  (ops/pallas_kernels.fp8_matmul) — the MXU runs e4m3 pairs at the int8
  rate (2× bf16). A dtype policy for step-time experiments (the BENCH fp8
  transformer entry), NOT numerics-preserving: off (default) keeps the
  native-dtype matmul.
- data_num_workers: default worker count for the native data runtime
  (paddle_tpu/data/, docs/data.md): PyReader.decorate_* calls that do not
  pass num_workers explicitly use this many multiprocess decode workers;
  0 (default) keeps the single-threaded feeder path.
- data_ring_slots: shared-memory ring capacity in batch slabs; 0 (default)
  auto-sizes to max(4, 2 * num_workers).
- data_prefetch: device-staged batches held ahead of the consumer (the
  double-buffer depth — batch k+1..k+prefetch transfer while step k runs).
- data_start_method: multiprocessing start method for decode workers.
  "fork" (default) is fast and accepts closures; use "spawn" when the
  parent process already initialized a TPU backend (decode fns must then
  be picklable module-level callables).
- data_max_worker_restarts: respawn budget per worker slot under the
  resilience retry policy before the runtime surfaces a fatal error.
- elastic_step_deadline_s: step-deadline for the elastic Supervisor's
  watchdog (resilience/elastic.py): a supervised step with no heartbeat for
  this many seconds counts a watchdog stall, takes an emergency checkpoint
  when the step returns, and raises FatalError; 0.0 (default) disables.
- elastic_nan_budget: consecutive bad (NaN-skipped / non-finite-loss) steps
  the Supervisor tolerates before rolling back to the last committed
  elastic checkpoint.
- elastic_rollback_budget: NaN-storm rollbacks before the Supervisor gives
  up with FatalError (progress is impossible from this state).
- elastic_barrier_timeout_s: how long the elastic checkpoint writers wait
  on cross-host markers (neighbor shard for the replica copy, rank 0's
  commit barrier) before DeadlineExceeded.
- pass_pipeline: graph-pass pipeline both executors apply at the lowering
  choke point (paddle_tpu/passes, docs/passes.md): a preset name
  ("training_default", "inference", or "training_fused" — the latter adds
  the Pallas kernel-substitution taggers) or a comma-separated pass list;
  "" (default) disables. ParallelExecutor's BuildStrategy.pass_pipeline
  (or BuildStrategy.fuse_kernels=True) overrides this per executor when
  set.
- pass_debug_dir: when set, the PassManager writes per-pass debug dumps
  into this directory — before/after graphviz of block 0 (via
  debugger.draw_block_graphviz) and a textual op diff, named
  <NN>_<pass>_{before,after}.dot / <NN>_<pass>_ops.diff; "" (default)
  disables.
- static_verify: run the whole-program static analyzer (paddle_tpu/analysis,
  docs/static_analysis.md) at every compile seam — Executor.run and
  ParallelExecutor.run executable-cache misses, aot_serve_lowering (the
  serving/generation model-load path), and the pass pipeline (stage 0 plus a
  structural re-verification after every pass). Error-severity fluidlint
  findings raise StaticVerifyError with op/var provenance BEFORE tracing;
  warnings count into the observability registry. Verification never
  mutates the program, so outputs are bit-identical with the flag off.
  False (default) skips the gate entirely.
- eager_delete_tensor_gb / fraction_of_gpu_memory_to_use /
  paddle_num_threads: accepted for API compatibility; storage lifetime and
  threading are XLA/PJRT-owned here (documented no-ops).
"""

import os

__all__ = ["get_flags", "set_flags"]

_DEFAULTS = {
    "check_nan_inf": False,
    "benchmark": False,
    "eager_delete_tensor_gb": -1.0,
    "fraction_of_gpu_memory_to_use": 0.92,
    "paddle_num_threads": 1,
    "cpu_deterministic": False,
    "rpc_max_retry": 3,
    "rpc_deadline": 120.0,
    "rpc_op_deadline": 30.0,
    "resilience_nan_guard": False,
    "resilience_lr_decay": 0.5,
    "dist_init_max_retry": 3,
    "profile_ops": False,
    "telemetry_dir": "",
    "telemetry_interval_steps": 50,
    "telemetry_log_every": 0,
    "tensor_stats": "",
    "nan_provenance": False,
    "trace_dir": "",
    "trace_sample": 1.0,
    "trace_slow_ms": 500.0,
    "trace_ring": 4096,
    "flightrec_dir": "",
    "flightrec_max_bundles": 16,
    "flightrec_min_interval_s": 2.0,
    "serving_cache_dir": "",
    "paged_flash": "auto",
    "gemm_double_buffer": "auto",
    "quantized_gemm": "auto",
    "fp8_matmul": False,
    "data_num_workers": 0,
    "data_ring_slots": 0,
    "data_prefetch": 2,
    "data_start_method": "fork",
    "data_max_worker_restarts": 4,
    "elastic_step_deadline_s": 0.0,
    "elastic_nan_budget": 3,
    "elastic_rollback_budget": 2,
    "elastic_barrier_timeout_s": 120.0,
    "pass_pipeline": "",
    "pass_debug_dir": "",
    "static_verify": False,
}

_flags = {}


def _coerce(template, raw):
    if isinstance(template, bool):
        return str(raw).lower() in ("1", "true", "yes", "on")
    return type(template)(raw)


def _init():
    import warnings

    for name, default in _DEFAULTS.items():
        env = os.environ.get("FLAGS_" + name)
        if env is None:
            _flags[name] = default
            continue
        try:
            _flags[name] = _coerce(default, env)
        except (TypeError, ValueError):
            # a malformed env var must not break `import paddle_tpu`
            warnings.warn(
                "ignoring malformed FLAGS_%s=%r (expected %s)"
                % (name, env, type(default).__name__)
            )
            _flags[name] = default


_init()


def get_flags(names=None):
    if names is None:
        return dict(_flags)
    if isinstance(names, str):
        return {names: _flags[names]}
    return {n: _flags[n] for n in names}


def set_flags(flags):
    for name, value in flags.items():
        name = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
        if name not in _flags:
            raise KeyError("unknown flag %r (known: %s)" % (name, sorted(_flags)))
        _flags[name] = _coerce(_DEFAULTS[name], value)
