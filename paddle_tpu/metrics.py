"""Host-side streaming metrics (reference python/paddle/fluid/metrics.py:
MetricBase, Accuracy, Precision, Recall, Auc, EditDistance, CompositeMetric,
DetectionMAP)."""

import numpy as np

__all__ = [
    "MetricBase",
    "Accuracy",
    "Precision",
    "Recall",
    "Auc",
    "CompositeMetric",
    "ChunkEvaluator",
    "EditDistance",
]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k in list(self.__dict__):
            if not k.startswith("_"):
                self.__dict__[k] = 0.0

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no updates to Accuracy yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += float(np.sum((preds == 1) & (labels == 1)))
        self.fp += float(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += float(np.sum((preds == 1) & (labels == 1)))
        self.fn += float(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1] if preds.ndim > 1 else preds.reshape(-1)
        bucket = np.clip(
            (pos_prob * self._num_thresholds).astype(int), 0, self._num_thresholds
        )
        np.add.at(self._stat_pos, bucket[labels != 0], 1)
        np.add.at(self._stat_neg, bucket[labels == 0], 1)

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp0 = np.concatenate([[0], tp[:-1]])
        fp0 = np.concatenate([[0], fp[:-1]])
        return float(np.sum((fp - fp0) * (tp + tp0) / 2.0) / (tot_pos * tot_neg))


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = (
            self.num_correct_chunks / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            self.num_correct_chunks / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall
            else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no updates to EditDistance yet")
        return (
            self.total_distance / self.seq_num,
            self.instance_error / self.seq_num,
        )
