"""Eager data parallelism: ParallelEnv + DataParallel over the device mesh.

Reference analog: python/paddle/fluid/dygraph/parallel.py (the reference's
immediate post-1.2 trajectory): `Env`/`ParallelEnv` describes the rank
layout, `prepare_context` boots NCCL, and `DataParallel` wraps a Layer so
that after backward() the trainer calls `apply_collective_grads()` to
all-reduce gradients across ranks before the optimizer step.

TPU-first redesign: one process drives ALL local devices SPMD, so rank
bookkeeping and explicit grad all-reduce disappear into GSPMD:
- inputs are sharded batch-wise over the mesh's 'dp' axis at the wrapper
  boundary (jax.device_put with a NamedSharding — the data never needs a
  per-rank copy loop);
- parameters are replicated once at wrap time;
- eager ops on sharded arrays execute SPMD per call, and the tape's
  jax.vjp closures produce GLOBALLY-reduced parameter gradients (the
  batch-contraction in dW IS the all-reduce, inserted by the partitioner
  over ICI) — so `scale_loss` and `apply_collective_grads` are semantic
  no-ops kept for API compatibility, documented per-method.

Multi-host: the same wrapper works over a multi-host mesh (parallel/
multihost.py initializes the runtime; jax.process_index() feeds
ParallelEnv.local_rank).
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .layers import Layer

__all__ = ["ParallelEnv", "Env", "prepare_context", "DataParallel"]


class ParallelEnv:
    """Rank layout (reference dygraph/parallel.py Env), with PROCESS
    semantics for the reference's pair: `nranks` = process count and
    `local_rank` = process index, so the standard per-rank data recipe
    (`chunks[local_rank] of nranks`) composes correctly with DataParallel —
    each process partitions the global data by process, and DataParallel
    shards that chunk over the process's local devices. (An earlier layout
    counted DEVICES in nranks while local_rank stayed the process index;
    on one host that made the recipe always pick chunk 0 of n_devices and
    silently train on 1/n of the data.) Device counts live on separate
    attributes: `local_device_count` (this process) and
    `data_parallel_degree` (devices in the data axis, = the divisor
    DataParallel shards batches by)."""

    def __init__(self, devices=None):
        devices = devices if devices is not None else jax.devices()
        self.nranks = jax.process_count()
        self.local_rank = jax.process_index()
        self.local_device_count = len(
            [d for d in devices if d.process_index == jax.process_index()]
        ) or len(devices)
        self.data_parallel_degree = len(devices)
        self.dev_id = devices[0].id
        self.current_endpoint = ""
        self.trainer_endpoints = []


Env = ParallelEnv  # reference exposed both names


class _ParallelStrategy:
    def __init__(self, env):
        self.nranks = env.nranks
        self.local_rank = env.local_rank
        self.trainer_endpoints = env.trainer_endpoints
        self.current_endpoint = env.current_endpoint


def prepare_context(strategy=None, devices=None):
    """reference prepare_context boots NCCL communicators; here the XLA
    runtime already owns the mesh, so this just reports the layout."""
    return strategy or _ParallelStrategy(ParallelEnv(devices))


class DataParallel(Layer):
    """Wrap an eager Layer for data-parallel execution over the mesh
    (reference dygraph/parallel.py DataParallel).

    Usage matches the reference:
        model = DataParallel(MyLayer(...))
        loss = model(x, y)            # x auto-sharded over 'dp'
        loss.backward()
        model.apply_collective_grads()  # compat no-op, see below
        optimizer.minimize(...)
    """

    def __init__(self, layers, strategy=None, devices=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy
        devices = devices if devices is not None else jax.devices()
        self._mesh = Mesh(np.asarray(devices), ("dp",))
        self._batch_sharding = NamedSharding(self._mesh, P("dp"))
        self._repl = NamedSharding(self._mesh, P())
        # replicate parameters once; eager updates preserve the layout
        for p in layers.parameters():
            p.value = jax.device_put(p.value, self._repl)

    @property
    def mesh(self):
        return self._mesh

    def parameters(self):
        return self._layers.parameters()

    def _shard(self, value):
        """Re-place a feed over the mesh. An eager Variable is sharded IN
        PLACE (same object back), preserving gradient tracking — the tape
        accumulates into the caller's Variable exactly as on the
        single-device path."""
        is_var = hasattr(value, "value")
        arr = value.value if is_var else jax.numpy.asarray(value)
        dp = self._mesh.shape["dp"]
        if arr.ndim >= 1 and arr.shape[0] % dp == 0:
            placed = jax.device_put(arr, self._batch_sharding)
        else:
            # scalars / indivisible leading dims replicate (same rule as
            # ParallelExecutor feeds)
            placed = jax.device_put(arr, self._repl)
        if is_var:
            value.value = placed
            return value
        return placed

    def __call__(self, *inputs):
        sharded = [self._shard(v) for v in inputs]
        return self._layers(*sharded)

    def forward(self, *args):  # pragma: no cover - __call__ overrides
        return self._layers.forward(*args)

    def scale_loss(self, loss):
        """Reference divides the loss by nranks because each process
        computes a LOCAL mean and NCCL all-reduce SUMS the grads. Here the
        loss already is the global batch mean (one SPMD computation), so
        scaling would be wrong — kept as the identity for API parity."""
        return loss

    def apply_collective_grads(self):
        """Reference: coalesce + nccl all-reduce every param.grad. Here the
        tape's vjp already contracted over the full (sharded) batch — the
        partitioner emitted the cross-device reduce inside the backward —
        so param gradients are already global. No-op for API parity."""
        return None
