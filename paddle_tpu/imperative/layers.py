"""Eager Layer / PyLayer bases (reference imperative/layers.py:25 PyLayer —
forward() over ops traced per call).

Layer.forward is written against jax.numpy values; __call__ traces the whole
body as one tape node (see base.Tape.trace), so backward() differentiates it
with jax.vjp and `jit()` compiles it without user changes."""

import jax
import jax.numpy as jnp
import numpy as np

from . import base
from .base import Variable, to_variable


class Layer:
    """Compose parameters + a jnp-based forward (reference imperative Layer).

    Subclass contract: create parameters in __init__ via create_parameter;
    implement forward(self, *arrays) taking/returning jax arrays (NOT eager
    Variables — the tape passes values in, wraps values out)."""

    def __init__(self, name_scope=None, dtype="float32"):
        self._dtype = dtype
        self._params = []
        self._sublayers = []

    def create_parameter(self, shape, dtype=None, initializer=None, name=None):
        dtype = dtype or self._dtype
        if initializer is None:
            fan_in = int(np.prod(shape[:-1])) or 1
            init = np.random.uniform(
                -1.0 / np.sqrt(fan_in), 1.0 / np.sqrt(fan_in), shape
            ).astype(dtype)
        elif callable(initializer):
            init = np.asarray(initializer(shape)).astype(dtype)
        else:
            init = np.full(shape, float(initializer), dtype)
        p = Variable(init, name=name)
        self._params.append(p)
        return p

    def add_sublayer(self, layer):
        self._sublayers.append(layer)
        return layer

    def parameters(self):
        out = list(self._params)
        for sub in self._sublayers:
            out.extend(sub.parameters())
        return out

    def forward(self, *args):
        raise NotImplementedError

    def _fn(self):
        return self.forward

    def __call__(self, *inputs):
        tape = base.current_tape()
        vars_in = [to_variable(v) for v in inputs]
        params = self.parameters()
        fn = self._fn()

        def run(*vals):
            xs = vals[: len(vars_in)]
            ps = vals[len(vars_in) :]
            return fn(*xs, *ps) if params else fn(*xs)

        if tape is None:
            out = run(*[v.value for v in vars_in], *[p.value for p in params])
            outs = [Variable(o) for o in (out if isinstance(out, tuple) else (out,))]
        else:
            outs = tape.trace(run, vars_in + params)
        return outs[0] if len(outs) == 1 else outs

    def jit(self):
        """Compile forward with XLA — same tape semantics, fused body (the
        capability the reference's per-op tracer could never offer)."""
        self._jitted = jax.jit(self.forward)
        self._fn = lambda: self._jitted
        return self


class PyLayer:
    """Custom-python forward/backward pair (reference imperative/layers.py
    PyLayer: static forward/backward over numpy)."""

    @staticmethod
    def forward(*inputs):
        raise NotImplementedError

    @staticmethod
    def backward(*douts):
        raise NotImplementedError

    @classmethod
    def __call__(cls, *a):
        return cls.apply(*a)

    @classmethod
    def apply(cls, *inputs):
        tape = base.current_tape()
        vars_in = [to_variable(v) for v in inputs]
        vals = [v.value for v in vars_in]
        out = cls.forward(*[np.asarray(v) for v in vals])
        outs_vals = out if isinstance(out, tuple) else (out,)
        outs = [Variable(jnp.asarray(o)) for o in outs_vals]
        if tape is not None:

            def vjp_fn(cots):
                gs = cls.backward(*[np.asarray(c) for c in cots])
                gs = gs if isinstance(gs, tuple) else (gs,)
                return tuple(jnp.asarray(g) for g in gs)

            # record ALL inputs: the user backward returns one grad per input
            # positionally; Tape.backward drops grads of stop_gradient vars
            if any(not v.stop_gradient for v in vars_in):
                tape.nodes.append(base._Node(vjp_fn, vars_in, outs))
        return outs[0] if len(outs) == 1 else outs
