"""Imperative (eager) mode — define-by-run on jax arrays with a gradient tape.

Reference analog: python/paddle/fluid/imperative/ (layers.py PyLayer base) +
paddle/fluid/imperative/tracer.{h,cc} — the embryonic eager mode of Fluid
1.2: ops execute immediately while a C++ tracer records an autograd tape
that backward() replays.

TPU-first redesign: eager values ARE jax arrays, so "executing an op" is just
calling its jnp/lowering function, and the tape doesn't need per-op grad
kernels — each traced call stores the jax.vjp residual closure, and
backward() walks the tape applying cotangents. A Layer's forward is any
jnp-composed function; its __call__ is traced as ONE tape node, which also
means XLA can jit the whole layer body (layer.jit()) without changing user
code — the per-op dispatch the reference's tracer did never exists here.
"""

from . import nn  # noqa: F401
from .base import Tape, Variable, enabled, guard, to_variable  # noqa: F401
from .layers import Layer, PyLayer  # noqa: F401
from .parallel import (  # noqa: F401
    DataParallel,
    Env,
    ParallelEnv,
    prepare_context,
)

__all__ = [
    "guard", "enabled", "to_variable", "Variable", "Layer", "PyLayer", "Tape",
    "nn", "ParallelEnv", "Env", "DataParallel", "prepare_context",
]
