"""Eager variables + gradient tape (reference imperative/tracer.{h,cc}
redesigned over jax.vjp; see package docstring)."""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

_state = {"tape": None}


def enabled():
    return _state["tape"] is not None


@contextlib.contextmanager
def guard(place=None):
    """`with fluid.imperative.guard():` — activates eager tracing (reference
    imperative.base.guard switched the tracer on)."""
    prev = _state["tape"]
    _state["tape"] = Tape()
    try:
        yield
    finally:
        _state["tape"] = prev


def current_tape():
    return _state["tape"]


class Variable:
    """Eager value: a jax array + accumulated gradient. The reference's
    VarBase (imperative/layers.h) held a tensor and grad slot the same way."""

    def __init__(self, value, stop_gradient=False, name=None):
        self.value = jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self.name = name
        self._grad = None

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def numpy(self):
        return np.asarray(self.value)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def _accum(self, g):
        self._grad = g if self._grad is None else self._grad + g

    def backward(self):
        """Reverse the tape from this (scalar) variable (reference
        tracer.cc backward pass over the recorded ops)."""
        tape = current_tape()
        if tape is None:
            raise RuntimeError("backward() outside imperative.guard()")
        if self.value.size != 1:
            raise ValueError("backward() needs a scalar loss")
        tape.backward(self)

    def __repr__(self):
        return "imperative.Variable(shape=%s, dtype=%s)" % (self.shape, self.dtype)


def to_variable(value, block=None, name=None):
    if isinstance(value, Variable):
        return value
    return Variable(value, name=name)


class _Node:
    __slots__ = ("vjp_fn", "inputs", "outputs")

    def __init__(self, vjp_fn, inputs, outputs):
        self.vjp_fn = vjp_fn
        self.inputs = inputs
        self.outputs = outputs


class Tape:
    def __init__(self):
        self.nodes = []

    def trace(self, fn, inputs):
        """Run fn(*arrays) under jax.vjp, record the node, return eager
        Variables. Differentiable leaves are the float inputs with
        stop_gradient=False."""
        leaves = [
            v
            for v in inputs
            if not v.stop_gradient and jnp.issubdtype(v.value.dtype, jnp.inexact)
        ]
        closed = [v.value for v in inputs]
        leaf_pos = [i for i, v in enumerate(inputs) if v in leaves]

        def f(*leaf_vals):
            vals = list(closed)
            for p, lv in zip(leaf_pos, leaf_vals):
                vals[p] = lv
            out = fn(*vals)
            return out if isinstance(out, tuple) else (out,)

        primals, vjp_fn = jax.vjp(f, *[v.value for v in leaves])
        outs = [Variable(p) for p in primals]
        if leaves:
            self.nodes.append(_Node(vjp_fn, leaves, outs))
        return outs

    def backward(self, root):
        root._accum(jnp.ones_like(root.value))
        for node in reversed(self.nodes):
            if all(o._grad is None for o in node.outputs):
                continue  # no cotangent reached this node
            cots = tuple(
                o._grad if o._grad is not None else jnp.zeros_like(o.value)
                for o in node.outputs
            )
            grads = node.vjp_fn(cots)
            for v, g in zip(node.inputs, grads):
                # PyLayer nodes list ALL inputs (user backward returns grads
                # positionally); stop_gradient inputs discard theirs here so
                # position i's grad can never land on a different variable
                if not v.stop_gradient:
                    v._accum(g)
