"""Eager Layer library (reference trajectory: imperative/nn.py grew Conv2D/
Pool2D/FC/BatchNorm/Embedding in the releases following 1.2 — this provides
the same usability tier over our tape, each layer a Layer subclass whose
forward is jnp math, so tape.backward()/jit() work unchanged).

Shapes/attrs mirror the graph-mode layers (layers/nn.py) where both exist;
docstrings cite the graph op each eager layer corresponds to.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .layers import Layer

__all__ = [
    "FC",
    "Conv2D",
    "Pool2D",
    "BatchNorm",
    "Embedding",
    "LayerNorm",
    "SGDOptimizer",
    "AdamOptimizer",
]


class FC(Layer):
    """Eager fully-connected (graph analog: layers.fc / mul+elementwise_add).
    Flattens trailing dims like num_flatten_dims=1."""

    def __init__(self, size, input_dim, act=None, bias_attr=True, dtype="float32"):
        super().__init__(dtype=dtype)
        self._size = size
        self._act = act
        self.weight = self.create_parameter([input_dim, size])
        self.bias = self.create_parameter([size], initializer=0.0) if bias_attr else None

    def forward(self, x, *params):
        w = params[0]
        b = params[1] if self.bias is not None else None
        x2 = x.reshape(x.shape[0], -1)
        y = x2 @ w
        if b is not None:
            y = y + b
        return _apply_act(y, self._act)


def _apply_act(y, act):
    if act is None:
        return y
    if act == "relu":
        return jnp.maximum(y, 0)
    if act == "tanh":
        return jnp.tanh(y)
    if act == "sigmoid":
        return jax.nn.sigmoid(y)
    if act == "softmax":
        return jax.nn.softmax(y, axis=-1)
    raise ValueError("unsupported act %r" % act)


class Conv2D(Layer):
    """Eager NCHW conv (graph analog: layers.conv2d / conv2d op)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, bias_attr=True, dtype="float32"):
        super().__init__(dtype=dtype)
        fs = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size,) * 2
        st = stride if isinstance(stride, (list, tuple)) else (stride,) * 2
        pd = padding if isinstance(padding, (list, tuple)) else (padding,) * 2
        self._stride, self._padding, self._groups, self._act = st, pd, groups, act
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, fs[0], fs[1]]
        )
        self.bias = (
            self.create_parameter([num_filters], initializer=0.0) if bias_attr else None
        )

    def forward(self, x, *params):
        w = params[0]
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=self._stride,
            padding=[(self._padding[0],) * 2, (self._padding[1],) * 2],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self._groups,
        )
        if self.bias is not None:
            y = y + params[1][None, :, None, None]
        return _apply_act(y, self._act)


class Pool2D(Layer):
    """Eager pool (graph analog: layers.pool2d / pool2d op)."""

    def __init__(self, pool_size=2, pool_type="max", pool_stride=None,
                 pool_padding=0, global_pooling=False, dtype="float32"):
        super().__init__(dtype=dtype)
        self._size = (pool_size,) * 2 if np.isscalar(pool_size) else tuple(pool_size)
        self._stride = (
            self._size if pool_stride is None
            else ((pool_stride,) * 2 if np.isscalar(pool_stride) else tuple(pool_stride))
        )
        self._pad = (pool_padding,) * 2 if np.isscalar(pool_padding) else tuple(pool_padding)
        self._type = pool_type
        self._global = global_pooling

    def forward(self, x):
        if self._global:
            return jnp.mean(x, axis=(2, 3), keepdims=True) if self._type == "avg" \
                else jnp.max(x, axis=(2, 3), keepdims=True)
        dims = (1, 1) + self._size
        strides = (1, 1) + self._stride
        pads = ((0, 0), (0, 0), (self._pad[0],) * 2, (self._pad[1],) * 2)
        if self._type == "max":
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides, pads)
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, pads)
        # exclusive average like the graph pool2d op's default: padded
        # positions don't count toward the divisor
        ones = jnp.ones(x.shape[2:], x.dtype)[None, None]
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pads)
        return s / cnt


class Embedding(Layer):
    """Eager embedding lookup (graph analog: layers.embedding / lookup_table)."""

    def __init__(self, size, padding_idx=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(list(size))

    def forward(self, ids, *params):
        w = params[0]
        ids = ids.reshape(ids.shape[0], -1).astype(jnp.int32)
        out = jnp.take(w, ids, axis=0)
        if self._padding_idx is not None:
            mask = (ids != self._padding_idx)[..., None]
            out = out * mask.astype(out.dtype)
        return out


class BatchNorm(Layer):
    """Eager batch norm over NCHW/NC (graph analog: batch_norm op). Train
    mode normalizes with batch stats and maintains running stats as
    non-trainable buffers updated OUTSIDE the tape (an eager convenience the
    graph op does in-graph); eval mode uses the running stats."""

    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5, dtype="float32"):
        super().__init__(dtype=dtype)
        self._momentum, self._eps = momentum, epsilon
        self.scale = self.create_parameter([num_channels], initializer=1.0)
        self.shift = self.create_parameter([num_channels], initializer=0.0)
        self._mean = np.zeros(num_channels, dtype)
        self._var = np.ones(num_channels, dtype)
        self.training = True

    def forward(self, x, *params):
        scale, shift = params
        axes = (0,) + tuple(range(2, x.ndim))
        if self.training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
        else:
            mean = jnp.asarray(self._mean)
            var = jnp.asarray(self._var)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        y = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + self._eps)
        return y * scale.reshape(shape) + shift.reshape(shape)

    def __call__(self, *inputs):
        out = super().__call__(*inputs)
        if self.training:
            # running-stat update: reduce on DEVICE, transfer only the [C]
            # results (a host-side recompute would sync the full activation)
            x = inputs[0].value if hasattr(inputs[0], "value") else jnp.asarray(inputs[0])
            axes = (0,) + tuple(range(2, x.ndim))
            m = self._momentum
            self._mean = m * self._mean + (1 - m) * np.asarray(jnp.mean(x, axis=axes))
            self._var = m * self._var + (1 - m) * np.asarray(jnp.var(x, axis=axes))
        return out

    def eval(self):
        self.training = False
        return self

    def train(self):
        self.training = True
        return self


class LayerNorm(Layer):
    """Eager layer norm over the last dim (graph analog: layer_norm op)."""

    def __init__(self, normalized_shape, epsilon=1e-5, dtype="float32"):
        super().__init__(dtype=dtype)
        self._eps = epsilon
        n = int(np.prod(np.atleast_1d(normalized_shape)))
        self.scale = self.create_parameter([n], initializer=1.0)
        self.shift = self.create_parameter([n], initializer=0.0)

    def forward(self, x, *params):
        scale, shift = params
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + self._eps) * scale + shift


class SGDOptimizer:
    """Eager SGD over Layer.parameters() (graph analog: optimizer.SGD —
    here a step() consuming each param's tape gradient)."""

    def __init__(self, parameters, learning_rate=0.01):
        self._params = list(parameters)
        self._lr = learning_rate

    def step(self):
        for p in self._params:
            if p._grad is not None:
                p.value = p.value - self._lr * p._grad

    def clear_gradients(self):
        for p in self._params:
            p.clear_gradient()


class AdamOptimizer:
    """Eager Adam (graph analog: optimizer.Adam; same update math as the
    adam op lowering, ops/core_ops.py)."""

    def __init__(self, parameters, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        self._params = list(parameters)
        self._lr, self._b1, self._b2, self._eps = learning_rate, beta1, beta2, epsilon
        self._m = [jnp.zeros_like(p.value) for p in self._params]
        self._v = [jnp.zeros_like(p.value) for p in self._params]
        self._t = 0

    def step(self):
        self._t += 1
        b1, b2 = self._b1, self._b2
        lr_t = self._lr * (1 - b2 ** self._t) ** 0.5 / (1 - b1 ** self._t)
        for i, p in enumerate(self._params):
            g = p._grad
            if g is None:
                continue
            self._m[i] = b1 * self._m[i] + (1 - b1) * g
            self._v[i] = b2 * self._v[i] + (1 - b2) * jnp.square(g)
            p.value = p.value - lr_t * self._m[i] / (jnp.sqrt(self._v[i]) + self._eps)

    def clear_gradients(self):
        for p in self._params:
            p.clear_gradient()
