"""Virtual-device platform bootstrap shared by tests/conftest.py and the
driver's dryrun path (__graft_entry__.dryrun_multichip).

The axon sitecustomize force-registers the TPU plugin and overrides
JAX_PLATFORMS at interpreter start, so setting env vars alone is not enough:
jax.config must also be flipped before the first backend lookup.
"""

import os
import re


def force_virtual_cpu_devices(n_devices):
    """Ensure jax will expose >= n_devices virtual CPU devices.

    Must be called before the first jax backend use (jax.devices() etc.).
    Returns the exception raised by the platform flip, or None on success —
    callers can fold it into their own error messages.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % n_devices
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), "--xla_force_host_platform_device_count=%d" % n_devices
        )

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # backend already initialized on another platform
        return e
    return None
