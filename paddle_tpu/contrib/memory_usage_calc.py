"""Estimate a program's activation/parameter memory (reference
python/paddle/fluid/contrib/memory_usage_calc.py:46 memory_usage — sums var
bytes with the batch dim substituted). On TPU this is the pre-compile HBM
sanity check: XLA's actual footprint differs (fusion, rematerialization,
donation), but the estimate bounds the working set the same way the
reference's did for GPU memory planning."""

__all__ = ["memory_usage"]

_DTYPE_BYTES = {
    "float64": 8,
    "float32": 4,
    "bfloat16": 2,
    "float16": 2,
    "int64": 8,
    "int32": 4,
    "int16": 2,
    "int8": 1,
    "uint8": 1,
    "bool": 1,
}


def memory_usage(program, batch_size):
    """Total estimated bytes for one iteration at `batch_size` (sums every
    var across blocks; -1 dims take batch_size, like the reference)."""
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    total = 0.0
    for i in range(program.num_blocks):
        block = program.block(i)
        for var in block.vars.values():
            if var.shape is None or var.dtype is None:
                continue
            n = 1
            for d in var.shape:
                n *= batch_size if d in (-1, None) else d
            total += n * _DTYPE_BYTES.get(str(var.dtype), 4)
    return total
