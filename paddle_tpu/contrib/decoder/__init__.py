from . import beam_search_decoder  # noqa: F401
from .beam_search_decoder import (  # noqa: F401
    BeamSearchDecoder,
    InitState,
    StateCell,
    TrainingDecoder,
)

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]
