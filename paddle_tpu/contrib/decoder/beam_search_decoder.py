"""Class-based seq2seq decoder API: InitState / StateCell / TrainingDecoder /
BeamSearchDecoder.

Reference analog: python/paddle/fluid/contrib/decoder/beam_search_decoder.py
(InitState:43, StateCell:159, TrainingDecoder:384, BeamSearchDecoder:523) —
the user defines per-step state math ONCE in a StateCell updater and reuses
it for teacher-forced training (TrainingDecoder over DynamicRNN) and beam
decode (BeamSearchDecoder over a While loop with beam_search ops).

TPU-first redesign: the reference grows/shrinks LoD beams dynamically
(sequence_expand, lod_reset, early-stop Switch on empty beams); here beams
are DENSE — batch*beam_size rows fixed for the whole decode (the same
padded-dense convention as layers.beam_search / models/machine_translation),
states reordered per step by the beam's parent indices with a gather. The
decode loop is one XLA While with static shapes; finished beams ride along
holding end_id (the beam_search op's end_id contract) instead of shrinking
the batch, so there is no early-stop block — the loop runs max_len steps.
"""

import numpy as np

from ... import layers
from ...framework import Variable, default_main_program
from ...param_attr import ParamAttr

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class InitState(object):
    """Initial state of a decoder cell (reference InitState:43): either an
    explicit `init` Variable, or (shape, value, dtype) to be materialized
    against the batch at decode time."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            if shape is None:
                raise ValueError("InitState needs `init` or `shape`")
            self._init = None
            self._shape = list(shape)
            self._value = float(value)
            self._dtype = dtype
        else:
            self._init = init_boot
        self._need_reorder = need_reorder

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder

    def materialize(self, batch_ref):
        """Concrete init tensor: the explicit var, or a batch-shaped fill."""
        if self._init is not None:
            return self._init
        from ...layers.tensor import fill_constant_batch_size_like

        return fill_constant_batch_size_like(
            batch_ref, shape=[-1] + self._shape[1:] if len(self._shape) > 1
            else [-1] + self._shape, dtype=self._dtype, value=self._value,
        )


class StateCell(object):
    """Per-step state machine (reference StateCell:159): `states` maps name →
    InitState, `inputs` maps name → Variable-or-None (None = fed per step),
    the @state_updater function reads inputs/states and set_state()s the new
    values; the enclosing decoder provides where states live."""

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._state_names = list(states)
        self._out_state = out_state
        self._updater = None
        self._cur_states = {}
        self._new_states = {}
        self._cur_inputs = {}

    def state_updater(self, updater):
        self._updater = updater
        return updater

    # -- used inside the updater ------------------------------------------
    def get_input(self, input_name):
        if input_name not in self._cur_inputs:
            raise ValueError("input %r not provided this step" % input_name)
        return self._cur_inputs[input_name]

    def get_state(self, state_name):
        if state_name in self._new_states:
            return self._new_states[state_name]
        if state_name not in self._cur_states:
            raise ValueError("state %r unknown" % state_name)
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        if state_name not in self._init_states:
            raise ValueError("state %r unknown" % state_name)
        self._new_states[state_name] = state_value

    # -- driven by the decoders -------------------------------------------
    def _bind(self, cur_states):
        self._cur_states = dict(cur_states)
        self._new_states = {}

    def compute_state(self, inputs):
        """Run the updater for this step with the given inputs (reference
        StateCell.compute_state:335)."""
        if self._updater is None:
            raise ValueError("no @state_updater registered")
        self._cur_inputs = dict(self._inputs)
        self._cur_inputs.update(inputs)
        self._updater(self)

    def update_states(self):
        """Commit set_state() values into the enclosing decoder's storage."""
        if self._commit is None:
            raise ValueError("update_states() outside a decoder block")
        self._commit(self._new_states)
        self._cur_states.update(self._new_states)
        self._new_states = {}

    def out_state(self):
        return self.get_state(self._out_state)

    _commit = None


class TrainingDecoder(object):
    """Teacher-forced decoding over DynamicRNN (reference TrainingDecoder:384):
    step_input slices the target sequence, the StateCell holds the recurrent
    state as RNN memories, output() collects per-step outputs."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._state_cell = state_cell
        self._status = self.BEFORE_DECODER
        self._drnn = layers.DynamicRNN(name=name)
        self._memories = {}

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._drnn

    def block(self):
        decoder = self

        class _Ctx(object):
            def __enter__(ctx):
                decoder._status = decoder.IN_DECODER
                ctx._inner = decoder._drnn.block()
                ctx._inner.__enter__()
                return ctx

            def __exit__(ctx, *exc):
                out = ctx._inner.__exit__(*exc)
                decoder._status = decoder.AFTER_DECODER
                decoder._state_cell._commit = None
                return out

        return _Ctx()

    def step_input(self, x):
        self._assert_in_decoder_block("step_input")
        seq_len = None
        len_name = getattr(x, "_len_name", None)
        if len_name is not None:
            seq_len = x.block._var_recursive(len_name)
        inp = self._drnn.step_input(x, seq_len=seq_len)
        # first sequence input pins the batch: materialize state memories
        if not self._memories:
            for name, init in self._state_cell._init_states.items():
                self._memories[name] = self._drnn.memory(
                    init=init.materialize(x)
                )
            self._state_cell._bind(self._memories)

            def commit(new_states):
                for sname, val in new_states.items():
                    self._drnn.update_memory(self._memories[sname], val)
                    self._memories[sname] = val

            self._state_cell._commit = commit
        return inp

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        return self._drnn.static_input(x)

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._drnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != self.AFTER_DECODER:
            raise ValueError("call the TrainingDecoder after its block")
        return self._drnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != self.IN_DECODER:
            raise ValueError("%s() must run inside decoder.block()" % method)


class BeamSearchDecoder(object):
    """Beam-search decode over the shared StateCell (reference
    BeamSearchDecoder:523). Dense TPU loop: batch*beam_size rows, states
    gathered by parent index each step; the embedding and output projection
    are created under `name` so training-side parameters can be shared by
    naming them identically."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50, sparse_emb=True,
                 max_len=100, beam_size=4, end_id=1, name=None):
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = min(topk_size, target_dict_dim)
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._name = name or "beam_search_decoder"
        self._decoded = None

    @property
    def state_cell(self):
        return self._state_cell

    def _tile_beam(self, x, n):
        batch = n // self._beam_size
        if x.shape[0] is None or x.shape[0] < 0:
            # encoder-side tensors carry a dynamic batch at build time; the
            # decode is static-n, so pin the batch (a no-op slice at runtime)
            x = layers.slice(x, axes=[0], starts=[0], ends=[batch])
        e = layers.unsqueeze(x, [1])
        tiled = layers.expand(e, [1, self._beam_size] + [1] * (len(x.shape) - 1))
        return layers.reshape(tiled, [n] + list(x.shape[1:]))

    def decode(self):
        """Build the decode loop (reference decode:653). Override for a
        custom per-step computation."""
        beam = self._beam_size
        batch = self._init_ids.shape[0]
        if batch is None or batch < 0:
            raise ValueError(
                "BeamSearchDecoder needs a static batch dim on init_ids "
                "(declare the data layer with append_batch_size=False and a "
                "fixed shape) — the dense beam layout is batch*beam_size rows "
                "with static shapes"
            )
        n = batch * beam
        cell = self._state_cell

        # dense beam tiling with the kInitialScore trick: only beam slot 0
        # is live initially, so step 1 expands each batch row into its beams
        pre_ids = self._tile_beam(self._init_ids, n)
        init_score_mask = np.zeros((n, 1), np.float32)
        init_score_mask[np.arange(n) % beam != 0] = -1e9
        pre_scores = layers.elementwise_add(
            self._tile_beam(self._init_scores, n),
            layers.assign(init_score_mask),
        )

        states = {}
        for sname, init in cell._init_states.items():
            states[sname] = layers.assign(
                self._tile_beam(init.materialize(self._init_ids), n)
            )
        static_feeds = {
            k: self._tile_beam(v, n) for k, v in self._input_var_dict.items()
        }

        ids_arr = layers.create_array("int64", shape=[self._max_len, n, 1])
        scores_arr = layers.create_array("float32", shape=[self._max_len, n, 1])
        parents_arr = layers.create_array("int32", shape=[self._max_len, n])

        pre_ids_v = layers.assign(pre_ids)
        pre_scores_v = pre_scores

        i = layers.fill_constant([1], "int64", 0)
        tmax = layers.fill_constant([1], "int64", self._max_len)
        cond = layers.less_than(i, tmax)
        w = layers.While(cond)
        with w.block():
            emb = layers.embedding(
                pre_ids_v,
                size=[self._target_dict_dim, self._word_dim],
                param_attr=ParamAttr(name=self._name + "_trg_emb"),
                is_sparse=False,
            )
            emb = layers.reshape(emb, [n, self._word_dim])
            cell._bind(states)
            new_vals = {}
            cell._commit = new_vals.update
            feeds = {}
            for input_name in cell._inputs:
                feeds[input_name] = static_feeds.get(input_name, emb)
            cell.compute_state(inputs=feeds)
            scores = layers.fc(
                cell.out_state(),
                size=self._target_dict_dim,
                act="softmax",
                param_attr=ParamAttr(name=self._name + "_out_w"),
                bias_attr=ParamAttr(name=self._name + "_out_b"),
            )
            topk_scores, topk_indices = layers.topk(scores, k=self._topk_size)
            accu = layers.elementwise_add(
                layers.log(topk_scores), pre_scores_v, axis=0
            )
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids_v, pre_scores_v, topk_indices, accu,
                beam_size=beam, end_id=self._end_id, return_parent_idx=True,
            )
            layers.array_write(sel_ids, i, array=ids_arr)
            layers.array_write(sel_scores, i, array=scores_arr)
            layers.array_write(parent, i, array=parents_arr)
            cell.update_states()
            # write each state's step value back into its loop-carried var,
            # reordered by the beam's parent indices (the dense analog of the
            # reference's sequence_expand beam growth)
            for sname, var in states.items():
                val = new_vals.get(sname, var)
                layers.assign(layers.gather(val, parent), var)
            layers.assign(sel_ids, pre_ids_v)
            layers.assign(sel_scores, pre_scores_v)
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, tmax, cond=cond)
        cell._commit = None

        self._decoded = layers.beam_search_decode(
            ids_arr, scores_arr, beam_size=beam, end_id=self._end_id,
            parents=parents_arr,
        )

    def __call__(self):
        if self._decoded is None:
            raise ValueError("call decode() before the decoder")
        return self._decoded
