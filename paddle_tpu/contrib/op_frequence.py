"""Op-frequency statistics over a Program.

Reference analog: contrib/op_frequence.py op_freq_statistic — counts single-op
frequencies and adjacent (producer→consumer) op-pair frequencies, the input
signal its authors used to pick fusion-pass candidates. Same use here: pairs
that dominate are what to check XLA's fusion actually merges (via
profiler.device_op_profile) or what deserves a Pallas kernel.
"""

from collections import OrderedDict

from ..framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_2_op_freq): op-type counts and
    "producer,consumer" adjacent-pair counts, both sorted descending."""
    if not isinstance(program, Program):
        raise TypeError(
            "The input type should be Program, got %s" % type(program)
        )

    uni_op_freq = OrderedDict()
    adj_2_op_freq = OrderedDict()
    parameters = {p.name for p in program.global_block().all_parameters()}

    for op in program.global_block().ops:
        recorded = False
        for var_name in op.output_arg_names:
            if var_name in parameters or recorded:
                continue
            uni_op_freq[op.type] = uni_op_freq.get(op.type, 0) + 1
            recorded = True

    var_gen_op = {}
    for op in program.global_block().ops:
        for var_name in op.input_arg_names:
            if var_name in parameters:
                continue
            gens = var_gen_op.get(var_name)
            if gens:
                key = "%s,%s" % (gens[-1], op.type)
                adj_2_op_freq[key] = adj_2_op_freq.get(key, 0) + 1
        for var_name in op.output_arg_names:
            if var_name in parameters:
                continue
            var_gen_op.setdefault(var_name, []).append(op.type)

    uni = OrderedDict(
        sorted(uni_op_freq.items(), key=lambda kv: kv[1], reverse=True)
    )
    adj = OrderedDict(
        sorted(adj_2_op_freq.items(), key=lambda kv: kv[1], reverse=True)
    )
    return uni, adj
