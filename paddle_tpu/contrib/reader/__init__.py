from . import ctr_reader  # noqa: F401
from .ctr_reader import ctr_reader as ctr_reader_fn  # noqa: F401

__all__ = ["ctr_reader"]
