"""CTR file reader: async multi-threaded slot-file parsing into the program.

Reference analog: contrib/reader/ctr_reader.py — a `create_ctr_reader` op
whose C++ threads parse CTR slot files and push LoDTensor batches into a
blocking queue the program's read op pops. Here the same pipeline is built
from the existing TPU-native pieces: the native C++ MultiSlotDataFeed parser
threads (paddle_tpu/native, gzip-transparent), the AsyncExecutor's
fixed-shape batch assembly (bucketed padding so XLA sees few shapes), and a
PyReader staging thread that device_puts the next batch while the current
step runs. The returned reader binds its slot variables into the program;
`Executor.run` with no feed pops staged batches exactly like layers.py_reader.
"""

import threading

from ... import framework, native
from ...async_executor import AsyncExecutor
from ...data_feed_desc import DataFeedDesc
from ...py_reader import PyReader

__all__ = ["ctr_reader"]


class _CtrReader(object):
    """Handle with the reference reader lifecycle: start() begins the parse
    threads + staging; reset() tears down for the next pass; `vars` are the
    per-slot variables for the model to consume."""

    def __init__(self, data_feed, capacity, thread_num, batch_size, file_list,
                 name):
        program = framework.default_main_program()
        self.name = name
        self._desc = data_feed
        self._thread_num = max(1, int(thread_num))
        self._files = list(file_list)
        self._used = data_feed.used_slots()
        if not self._used:
            raise ValueError("data feed desc has no used slots (set_use_slots)")
        if batch_size:
            data_feed.batch_size = int(batch_size)
        block = program.current_block()
        self.vars = []
        for _, slot in self._used:
            if slot.name in block.vars:
                v = block.vars[slot.name]
            else:
                dtype = "float32" if slot.type == "float" else "int64"
                v = block.create_var(
                    name=slot.name, shape=[-1, -1], dtype=dtype,
                    is_data=True, stop_gradient=True,
                )
            self.vars.append(v)
        self._impl = PyReader(
            [v.name for v in self.vars], capacity=capacity,
        )
        self._feed = None
        readers = getattr(program, "_py_readers", None)
        if readers is None:
            readers = program._py_readers = []
        readers.append(self)

    def _batches(self):
        bs = self._desc.batch_size
        assemble = AsyncExecutor._assemble

        def gen():
            it = iter(self._feed)
            while True:
                batch = []
                try:
                    while len(batch) < bs:
                        batch.append(next(it))
                except StopIteration:
                    if batch:
                        yield assemble(None, batch, self._used, self.vars)
                    return
                yield assemble(None, batch, self._used, self.vars)

        return gen

    def start(self):
        self._feed = native.MultiSlotDataFeed(
            self._desc.native_slot_types(),
            queue_capacity=4 * self._desc.batch_size,
        )
        self._feed.start(self._files, nthreads=self._thread_num)
        self._impl.decorate_tensor_provider(self._batches())
        self._impl.start()

    def reset(self):
        self._impl.reset()
        if self._feed is not None:
            self._feed.join()
            self._feed = None

    def next_batch(self):
        return self._impl.next_batch()

    @property
    def started(self):
        return self._impl._started


def ctr_reader(feed_data=None, capacity=64, thread_num=4, batch_size=32,
               file_list=(), slots=None, name=None):
    """Create the CTR reader (reference contrib ctr_reader:47 signature).
    `slots` is a DataFeedDesc (or its textproto string/path) describing the
    slot schema; `feed_data` is accepted for signature parity (the reader
    creates/binds the slot variables itself, like the reference's
    `_copy_reader_var_` plumbing)."""
    desc = slots if isinstance(slots, DataFeedDesc) else DataFeedDesc(slots)
    return _CtrReader(desc, capacity, thread_num, batch_size, file_list,
                      name or "ctr_reader")
