"""Contrib tier (reference python/paddle/fluid/contrib/): quantize
transpiler, memory-usage estimator, beam-search decoder helpers. The pieces
that graduated into first-class modules here re-export from their new homes
so `fluid.contrib.*` call sites keep working."""

from ..transpiler.quantize_transpiler import QuantizeTranspiler  # noqa: F401
from . import decoder, memory_usage_calc, op_frequence, reader  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401

__all__ = ["QuantizeTranspiler", "memory_usage", "memory_usage_calc", "decoder", "reader", "op_freq_statistic"]
