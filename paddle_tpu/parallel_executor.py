"""ParallelExecutor: the fluid multi-device data-parallel API, compiled SPMD.

Reference analog: python/paddle/fluid/parallel_executor.py:32 +
framework/parallel_executor.cc:92 + framework/details/ (SURVEY.md §2.2). The
reference rewrites the program into a per-device SSA graph with explicit
ncclAllReduce nodes executed by a thread pool. The TPU-native equivalent is
GSPMD: ONE XLA module jitted over a jax.sharding.Mesh with the batch sharded
on the 'dp' axis and parameters replicated — the partitioner inserts the
gradient all-reduce over ICI automatically at the param-update seam, replacing
threads/streams/NCCL with compiled collectives.

BuildStrategy / ExecutionStrategy are kept API-compatible; most knobs are
no-ops by construction (XLA already fuses, orders collectives
deterministically, and GCs buffers), documented per-field.
"""

import weakref

import numpy as np

import jax
from jax.sharding import Mesh

from . import framework
from .executor import (
    _CompiledBlock,
    _MultiStepBlock,
    _PipelinedBlock,
    _apply_pass_pipeline,
    _as_feed_array,
    _flags_opprof,
    _telemetry_begin,
    _telemetry_record,
    global_scope,
)
from .framework import Variable

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy"]


class ReduceStrategy:
    """reference details/build_strategy.h ReduceStrategy"""

    AllReduce = 0
    Reduce = 1


class BuildStrategy:
    """Knobs from reference details/build_strategy.h (pybind.cc:746-833).
    On TPU: reduce_strategy maps AllReduce→gradient all-reduce with fully
    replicated optimizer state, Reduce→the ZeRO-1 tier (the reference's
    Reduce strategy likewise updated each parameter on ONE device and
    broadcast it back — reduce_op_handle.cc; here the update is sharded
    1/dp per rank instead of whole-param per owner): gradients
    reduce-scatter over 'dp', each rank updates its param+moment shard,
    params all-gather back, optimizer state stored sharded (÷dp memory).
    Fusion knobs are no-ops (XLA fuses); sequential/debug knobs are honored
    where meaningful."""

    ReduceStrategy = ReduceStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = 0
        self.debug_graphviz_path = ""
        self.enable_data_balance = False
        self.fuse_elewise_add_act_ops = False  # XLA fuses; kept for compat
        self.fuse_broadcast_op = False
        self.enable_sequential_execution = False
        self.memory_optimize = False
        self.num_trainers = 1
        self.trainer_id = 0
        # pipeline parallelism depth: >1 makes ParallelExecutor build a
        # dp×pp mesh (all remaining devices on 'dp') and lower the program
        # through the pipeline partitioner (executor._PipelinedBlock).
        # Ignored when an explicit mesh_config is passed — set MeshConfig(pp=)
        # there instead.
        self.pipeline_stages = 1
        # graph-pass pipeline applied before lowering (paddle_tpu/passes,
        # docs/passes.md): a manager.PRESETS name or comma-separated pass
        # list; "" disables. None (default) defers to FLAGS_pass_pipeline.
        self.pass_pipeline = None
        # True -> lower tagged matmul+bias[+act] / layer_norm(+residual) /
        # adam-run chains through the hand-tuned Pallas kernels (the
        # "training_fused" preset; docs/passes.md kernel substitution).
        # Only consulted when pass_pipeline is None — an explicit pipeline
        # always wins.
        self.fuse_kernels = False
        # declarative placement over the dp×fsdp×tp×sp×ep×pp mesh: a
        # parallel.ShardingRules (or an iterable of (regex, spec) pairs)
        # mapping param/activation names -> PartitionSpec tuples, LAST match
        # wins. Merged AFTER any program-attached rules
        # (parallel.program_rules), so these win ties. This is how tensor
        # parallelism and FSDP are requested — see docs/parallelism.md
        # "Sharding rules". Requires a mesh_config naming the axes used
        # (e.g. MeshConfig(fsdp=4, tp=2)); axes the mesh lacks degrade to
        # replication.
        self.sharding_rules = None

    def resolved_pass_pipeline(self):
        """The pipeline the executor should apply: pass_pipeline verbatim
        when set (even ""), else "training_fused" when fuse_kernels, else
        None (defer to FLAGS_pass_pipeline)."""
        if self.pass_pipeline is not None:
            return self.pass_pipeline
        if self.fuse_kernels:
            return "training_fused"
        return None


class ExecutionStrategy:
    """reference ExecutionStrategy (pybind.cc:746): thread counts and scope
    reuse are meaningless under one compiled XLA module; kept for compat."""

    def __init__(self):
        self.num_threads = 0
        self.use_cuda = False
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1
        # pp-tier knobs (no-ops unless the mesh has pp > 1):
        # pipeline_schedule: "gpipe" (all forwards then all backwards; O(m)
        # live activations per rank) or "1f1b" (interleaved one-forward-
        # one-backward; O(pp) live activations — same bubble fraction,
        # (pp-1)/(m+pp-1), much flatter memory at large m).
        self.pipeline_schedule = "gpipe"
        # microbatch count m per dp-local batch; None → pp (the minimum that
        # fills the pipeline once).
        self.num_microbatches = None


class ParallelExecutor:
    """Drop-in for fluid.ParallelExecutor (reference parallel_executor.py:32).

    use_cuda is accepted and ignored (we always target the jax default
    backend: TPU on hardware, the forced CPU mesh in tests)."""

    def __init__(
        self,
        use_cuda=False,
        loss_name=None,
        main_program=None,
        share_vars_from=None,
        exec_strategy=None,
        build_strategy=None,
        num_trainers=1,
        trainer_id=0,
        scope=None,
        devices=None,
        mesh_config=None,
    ):
        self._program = main_program or framework.default_main_program()
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._scope = scope or global_scope()
        if share_vars_from is not None:
            self._scope = share_vars_from._scope
        devices = devices if devices is not None else jax.devices()
        # reference: one rank per GPU per trainer (nccl_helper.h:115-120);
        # here: the mesh spans all local devices — pure 'dp' by default, or a
        # full dp×tp×sp×ep mesh via mesh_config (parallel.MeshConfig).
        # Multi-host (num_trainers>1) extends the mesh across processes (DCN).
        if mesh_config is not None:
            from .parallel import make_mesh

            self._mesh = make_mesh(mesh_config, devices)
        elif self._build_strategy.pipeline_stages > 1:
            from .parallel import MeshConfig, make_mesh

            self._mesh = make_mesh(
                MeshConfig(dp=-1, pp=self._build_strategy.pipeline_stages),
                devices,
            )
        else:
            self._mesh = Mesh(np.asarray(devices), ("dp",))
        self._cache = {}

    @property
    def device_count(self):
        """Number of ways the batch is split: dp × fsdp (FSDP shards the
        batch too — it is data parallelism with sharded storage)."""
        dp = self._mesh.shape.get("dp", self._mesh.size)
        return dp * self._mesh.shape.get("fsdp", 1)

    @property
    def _data_axes(self):
        """Mesh axes the batch dim shards over. Extent-1 axes are dropped so
        the default Mesh(devices, ('dp',)) and fsdp-less configs keep their
        exact old specs."""
        axes = tuple(
            a for a in ("dp", "fsdp") if self._mesh.shape.get(a, 1) > 1
        )
        return axes or ("dp",)

    @property
    def topology(self):
        """Mesh axis extents + host count, the identity an elastic
        checkpoint manifest records (resilience/async_ckpt.py): a later
        resume compares its own topology against the saved one only for
        bookkeeping — restore itself is topology-blind."""
        import jax

        out = {name: int(ext) for name, ext in self._mesh.shape.items()}
        try:
            out["num_hosts"] = int(jax.process_count())
        except RuntimeError:
            out["num_hosts"] = 1
        return out

    def _install_reader_sharding(self):
        """Hand this PE's data-parallel placement to the program's readers
        (data-runtime mode stages batches with it, so they arrive on the
        mesh already split over 'dp' — no gather/re-scatter between the
        staging thread and the compiled step). Per-array callable: fields
        whose batch dim doesn't divide the mesh stay replicated."""
        dp = self.device_count
        if dp <= 1:
            return
        mesh = self._mesh
        axes = self._data_axes
        from jax.sharding import NamedSharding, PartitionSpec

        def shard_for(arr):
            shape = getattr(arr, "shape", None)
            if not shape or shape[0] % dp != 0:
                return None
            spec = PartitionSpec(axes, *([None] * (len(shape) - 1)))
            return NamedSharding(mesh, spec)

        for reader in getattr(self._program, "_py_readers", []):
            if hasattr(reader, "set_device_sharding"):
                reader.set_device_sharding(shard_for)

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True,
            steps_per_run=1):
        """steps_per_run > 1 compiles k iterations into one SPMD XLA call
        (executor._MultiStepBlock over this mesh): `feed` is then a dict of
        stacked arrays with leading axis k (a feed LIST keeps its reference
        meaning of per-DEVICE dicts and is only valid for k=1); fetches come
        back stacked [k, ...]."""
        feed = feed if feed is not None else (feed_dict or {})
        _obs, _obs_t0 = _telemetry_begin()
        force_multi = False  # 1-batch epoch tail keeps the [k, ...] contract
        if not feed:
            # pull staged batches from started py_readers, like Executor.run
            from .executor import _resolve_reader_feed

            self._install_reader_sharding()
            feed, steps_per_run, force_multi = _resolve_reader_feed(
                self._program, steps_per_run
            )
        is_multi = steps_per_run > 1 or force_multi
        if isinstance(feed, (list, tuple)):
            if steps_per_run > 1:
                raise TypeError(
                    "with steps_per_run>1 feed must be a dict of stacked "
                    "arrays (leading axis k); a feed list means per-device "
                    "dicts (reference parallel_executor.py:183-213)"
                )
            # reference API form: one dict per device (reference
            # parallel_executor.py:183-213) — concatenate along the batch dim
            merged = {}
            for d in feed:
                if not isinstance(d, dict):
                    raise TypeError(
                        "feed must be a dict or a list of per-device dicts; got "
                        "list of %r" % type(d).__name__
                    )
                for k, v in d.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            feed = {k: np.concatenate(vs, axis=0) for k, vs in merged.items()}
        program = self._program
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]
        # graph-pass choke point, mirroring Executor.run (docs/passes.md);
        # BuildStrategy.pass_pipeline overrides FLAGS_pass_pipeline when set
        program = _apply_pass_pipeline(
            program, self._scope, list(feed.keys()), fetch_names,
            pipeline=self._build_strategy.resolved_pass_pipeline(),
        )
        block = program.global_block()
        feed_arrays = {}
        batch_dim = 1 if is_multi else 0  # stacked feeds: [k, N, ...]
        for name, value in feed.items():
            var = block.vars.get(name)
            arr = _as_feed_array(value, var)
            if (
                len(arr.shape) > batch_dim
                and arr.shape[batch_dim] % self.device_count != 0
            ):
                raise ValueError(
                    "batch dim %d of feed %r not divisible by device count %d "
                    "(reference PE splits the batch across devices the same way)"
                    % (arr.shape[batch_dim], name, self.device_count)
                )
            feed_arrays[name] = arr

        pp = self._mesh.shape.get("pp", 1)
        if pp > 1 and is_multi:
            raise NotImplementedError(
                "steps_per_run > 1 is not supported with pipeline "
                "parallelism yet; run one step per call on a pp mesh"
            )
        # declarative sharding rules: BuildStrategy's own (normalized to a
        # ShardingRules), merged by the compiled block AFTER any
        # program-attached rules. Both fingerprints go into the cache key —
        # rules hang off live objects and may grow between runs.
        from .parallel.sharding_rules import ShardingRules

        bs_rules = self._build_strategy.sharding_rules
        if bs_rules is not None and not isinstance(bs_rules, ShardingRules):
            bs_rules = ShardingRules(bs_rules)
        prog_rules = getattr(program, "_sharding_rules", None)
        key = (
            program._uid,
            program._version,
            tuple(sorted((n, a.shape, str(a.dtype)) for n, a in feed_arrays.items())),
            tuple(fetch_names),
            self._scope._uid,
            steps_per_run,
            force_multi and steps_per_run == 1,
            (
                self._exec_strategy.pipeline_schedule,
                self._exec_strategy.num_microbatches,
            )
            if pp > 1
            else None,
            # toggling FLAGS_tensor_stats must recompile (executor.py key
            # carries the same term)
            _flags_opprof()["tensor_stats"],
            bs_rules.fingerprint() if bs_rules is not None else None,
            prog_rules.fingerprint() if prog_rules is not None else None,
        )
        compiled = self._cache.get(key)
        _obs_cache_hit = compiled is not None
        if compiled is None:
            # FLAGS_static_verify (docs/static_analysis.md): mesh-aware lint —
            # the analyzer resolves sharding specs through the same Resolver
            # precedence the compile below uses
            from .analysis import maybe_static_verify

            maybe_static_verify(
                program, list(feed_arrays.keys()), fetch_names,
                scope=self._scope, mesh=self._mesh, rules=bs_rules,
                mode="inference" if getattr(program, "_is_test", False)
                else "training",
                where="parallel_executor",
            )
            # feed_ranks are UNSTACKED ranks: rank 0 (scalars) replicate
            feed_ranks = {
                n: np.ndim(a) - batch_dim for n, a in feed_arrays.items()
            }
            # ReduceStrategy.Reduce → ZeRO-1 over the dp axis (BuildStrategy
            # docstring); degrades to the replicated path when dp == 1
            zero1_axis = (
                "dp"
                if self._build_strategy.reduce_strategy == ReduceStrategy.Reduce
                and self._mesh.shape.get("dp", 1) > 1
                else None
            )
            if pp > 1:
                compiled = _PipelinedBlock(
                    program, block, list(feed_arrays.keys()), fetch_names,
                    self._scope, mesh=self._mesh, feed_ranks=feed_ranks,
                    zero1_axis=zero1_axis, sharding_rules=bs_rules,
                    loss_name=self._loss_name,
                    n_micro=self._exec_strategy.num_microbatches,
                    schedule=self._exec_strategy.pipeline_schedule,
                )
            elif is_multi:
                compiled = _MultiStepBlock(
                    program, block, list(feed_arrays.keys()), fetch_names,
                    self._scope, steps_per_run, mesh=self._mesh,
                    data_axes=self._data_axes, feed_ranks=feed_ranks,
                    zero1_axis=zero1_axis, sharding_rules=bs_rules,
                )
            else:
                compiled = _CompiledBlock(
                    program,
                    block,
                    list(feed_arrays.keys()),
                    fetch_names,
                    self._scope,
                    mesh=self._mesh,
                    data_axes=self._data_axes,
                    feed_ranks=feed_ranks,
                    zero1_axis=zero1_axis,
                    sharding_rules=bs_rules,
                )
            self._cache[key] = compiled

        # place the global batch sharded over the mesh before dispatch;
        # rank-0 feeds (scalars like a lr) cannot be batch-sharded — replicate
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self._mesh, P())
        sharded = {
            n: jax.device_put(
                a,
                compiled._feed_sharding
                if np.ndim(a) > batch_dim
                else repl,
            )
            for n, a in feed_arrays.items()
        }
        fetches = compiled(self._scope, sharded)
        # correlation seed for compiled_hlo(): abstract feed shapes only
        # (concrete arrays would pin a batch of device memory), same
        # contract as Executor._last_run
        self._last_run = (
            compiled,
            weakref.ref(self._scope),
            {
                n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                for n, a in sharded.items()
            },
        )
        result = [np.asarray(f) for f in fetches] if return_numpy else fetches
        if _obs is not None:
            # pp runs carry their schedule so the collector can group step
            # times by (pp, schedule, m) for the two-m-slope bubble gauge
            plan = getattr(compiled, "stage_plan", None)
            _telemetry_record(
                _obs, _obs_t0, compiled, _obs_cache_hit, False,
                steps_per_run if is_multi else 1, result, return_numpy,
                pp=pp if pp > 1 else None,
                n_micro=plan["n_micro"] if plan else None,
                schedule=plan["schedule"] if plan else None,
            )
        return result

    def compiled_hlo(self):
        """Post-optimization HLO text of the most recently run SPMD block
        (Executor.compiled_hlo analog). Every collective the GSPMD partition
        inserted — the gradient all-reduce, or the ZeRO-1 reduce-scatter /
        all-gather pair under ReduceStrategy.Reduce — is visible here with
        shapes and replica_groups; tools/comm_audit.py parses this text for
        the per-collective wire-volume audit. Served from the backend's
        compilation cache after a run, so this does not recompile."""
        last = getattr(self, "_last_run", None)
        if last is None:
            raise RuntimeError("compiled_hlo() needs a prior ParallelExecutor.run")
        compiled, scope_ref, feed_avals = last
        scope = scope_ref()
        if scope is None:
            raise RuntimeError(
                "compiled_hlo(): the scope of the last run no longer exists"
            )
        ro = {n: scope.vars[n] for n in compiled.ro_names}
        mut = {n: scope.vars[n] for n in compiled.mut_names}
        lowered = compiled.jitted.lower(feed_avals, ro, mut, scope.rng_key)
        return lowered.compile().as_text()

    def drop_local_exe_scopes(self):  # compat no-op: no per-device scopes
        pass
