"""LayerHelper: shared parameter-creation / op-append plumbing for all layers.

Reference analog: python/paddle/fluid/layer_helper.py — every layer function
constructs one of these to create parameters (registered in both the main and
startup programs, with the initializer op appended to the startup program),
create output variables, and append its ops to the main program.
"""

import copy

from . import framework, unique_name
from .framework import Parameter, Variable, default_main_program, default_startup_program
from .initializer import Constant, Xavier
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name", None)
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" % self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr", None))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr", None))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [copy.deepcopy(attr) for _ in range(length)]
        if len(attr) != length:
            raise ValueError("param_attr length mismatch")
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        return zip(inputs, attrs)

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("input dtype mismatch: %s vs %s" % (dtype, each.dtype))
        return dtype

    def create_parameter(
        self, attr, shape, dtype, is_bias=False, default_initializer=None
    ):
        """Create the Parameter in the main program and append its initializer
        op to the startup program (reference layer_helper.py:create_parameter)."""
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))

        shape = [int(s) for s in shape]
        # startup program owns the init op; main program owns the Parameter
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs(with_initializer=True)
        )
        attr.initializer(sp, startup_block)
        return self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs()
        )

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    # reference-era alias
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs
        )

    def create_or_get_global_variable(self, name, *args, **kwargs):
        block = self.main_program.global_block()
        if block.has_var(name):
            return block.var(name)
        return block.create_var(name=name, *args, persistable=True, **kwargs)

    def set_variable_initializer(self, var, initializer):
        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(
            name=var.name,
            shape=var.shape,
            dtype=var.dtype,
            persistable=True,
        )
        initializer(sv, startup_block)
        return sv

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var.name], "Y": [b.name]},
            outputs={"Out": [tmp.name]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act", None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        else:
            act = copy.deepcopy(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var.name]},
            outputs={"Out": [tmp.name]},
            attrs=act,
        )
        return tmp
