"""Ring attention: exact attention over sequences sharded across the 'sp'
mesh axis (context parallelism over ICI).

The reference (2018-era) has NO sequence parallelism — its long-sequence
answer was LoD ragged batching (SURVEY.md §5.7); this is the new capability
the TPU build adds. Algorithm (Liu et al. ring attention; public pattern):
each rank holds a (b, h, t_local, d) shard of Q/K/V along the sequence; K/V
chunks rotate around the ring via ppermute while each rank accumulates its
queries' attention with an online (streaming) softmax — max/denominator
corrections per incoming chunk — so the result is EXACT full attention
without ever materializing the (t, t) score matrix on one chip, and the
K/V transfer overlaps compute around the ring.

Causal masking uses global positions derived from each chunk's rank of
origin (after i rotations a rank holds the chunk of rank (me - i) mod n).

Two per-chunk compute tiers:

- **flash** (default when tile shapes allow): each ring step runs the Pallas
  flash-attention kernels (ops/pallas_kernels.py). The whole ring is one
  custom_vjp: the forward merges per-chunk (out_i, lse_i) with the online
  rescale and saves only (q, k, v, out, lse) per rank — O(t_local) memory;
  the backward re-runs the ring with the flash dQ/dKV kernels against the
  GLOBAL logsumexp (the flash-2 decomposition is exact per KV block, so
  per-chunk backward with global lse sums to the full gradient) while dK/dV
  accumulators rotate with their chunks and arrive home after n hops.
  Causal chunk scheduling is static-per-step: step 0 is the diagonal
  (causal kernel); later steps are fully-visible or fully-masked, selected
  by one lax.cond (the masked branch does no FLOPs) — the causal ring does
  ~half the work of the full ring.
- **dense** fallback (ragged tiles): the original einsum online-softmax
  steps differentiated by plain autodiff.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import pallas_kernels as pk
from .collectives import SHARD_MAP_CHECK_KW, axis_size, shard_map

__all__ = ["ring_attention", "ring_attention_sharded"]

NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name, causal, scale):
    """Runs inside shard_map: q,k,v are local (b, h, t_loc, d) shards."""
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape

    q_pos = me * t_loc + jnp.arange(t_loc)  # global positions of my queries

    def step(i, carry):
        k_cur, v_cur, m, l, o = carry
        src = (me - i) % n  # rank of origin of the chunk I currently hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new)

    m0 = jnp.full((b, h, t_loc), NEG_INF, q.dtype)
    l0 = jnp.zeros((b, h, t_loc), q.dtype)
    o0 = jnp.zeros((b, h, t_loc, d), q.dtype)
    carry = (k, v, m0, l0, o0)
    # unrolled python loop: n is a static mesh size, so XLA can pipeline the
    # ppermute of chunk i+1 behind the matmuls of chunk i
    for i in range(n):
        carry = step(i, carry)
    _, _, m, l, o = carry
    return o / jnp.maximum(l, 1e-20)[..., None]


# ---------------------------------------------------------------------------
# flash ring: whole-ring custom_vjp over the Pallas kernels
# ---------------------------------------------------------------------------


def _rot(x, axis_name, n):
    return lax.ppermute(x, axis_name, [(j, (j + 1) % n) for j in range(n)])


def _chunk_fwd(q, k_cur, v_cur, causal_diag, scale, interpret):
    """One ring step's flash forward on (b, h, t_loc, d): (out, lse)."""
    out, lse = pk._flash_forward(
        q, k_cur, v_cur, causal_diag, scale,
        None, None, interpret, with_lse=True,
    )
    return out, lse


def _chunk_bwd(q, k_cur, v_cur, out, lse, do, causal_diag, scale, interpret):
    """One ring step's flash backward against the GLOBAL lse."""
    return pk._flash_backward(
        q, k_cur, v_cur, out, lse, do, causal_diag, scale,
        None, None, interpret,
    )


def _merge(acc, m, l, o_i, lse_i):
    """Online merge of a normalized chunk (o_i, lse_i) into (acc, m, l)."""
    m_new = jnp.maximum(m, lse_i)
    alpha = jnp.exp(jnp.where(m == -jnp.inf, -jnp.inf, m - m_new))
    w = jnp.exp(lse_i - m_new)
    acc = acc * alpha[..., None] + o_i.astype(jnp.float32) * w[..., None]
    l = l * alpha + w
    return acc, m_new, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash_local(q, k, v, axis_name, causal, scale, interpret):
    out, _lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale, interpret)
    return out


def _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale, interpret):
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape

    acc = jnp.zeros((b, h, t_loc, d), jnp.float32)
    m = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        if causal and i == 0:
            # diagonal chunk: the only step needing an intra-chunk mask
            o_i, lse_i = _chunk_fwd(q, k_cur, v_cur, True, scale, interpret)
            acc, m, l = _merge(acc, m, l, o_i, lse_i)
        elif causal:
            # src = (me - i) % n: fully visible iff src < me, else fully
            # masked — one cond, and the masked branch does no attention work
            src = (me - i) % n

            def _vis(args):
                acc, m, l = args
                o_i, lse_i = _chunk_fwd(q, k_cur, v_cur, False, scale, interpret)
                return _merge(acc, m, l, o_i, lse_i)

            acc, m, l = lax.cond(src < me, _vis, lambda args: args, (acc, m, l))
        else:
            o_i, lse_i = _chunk_fwd(q, k_cur, v_cur, False, scale, interpret)
            acc, m, l = _merge(acc, m, l, o_i, lse_i)
        if i + 1 < n:
            k_cur = _rot(k_cur, axis_name, n)
            v_cur = _rot(v_cur, axis_name, n)
    out = (acc / jnp.maximum(l, 1e-20)[..., None]).astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-20))
    return out, lse


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale, interpret):
    out, lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale, interpret)
    # O(t_local) residuals — no per-step K/V chunks, no (t, t) scores
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, interpret, res, do):
    q, k, v, out, lse = res
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)

    dq = jnp.zeros(q.shape, jnp.float32)
    # dK/dV accumulators travel around the ring WITH their chunk and are
    # home again after the n-th hop
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        if causal and i == 0:
            dq_i, dk_i, dv_i = _chunk_bwd(
                q, k_cur, v_cur, out, lse, do, True, scale, interpret
            )
            dq += dq_i
            dk_acc += dk_i
            dv_acc += dv_i
        elif causal:
            src = (me - i) % n

            def _vis(args):
                dq, dk_acc, dv_acc = args
                dq_i, dk_i, dv_i = _chunk_bwd(
                    q, k_cur, v_cur, out, lse, do, False, scale, interpret
                )
                return dq + dq_i, dk_acc + dk_i, dv_acc + dv_i

            dq, dk_acc, dv_acc = lax.cond(
                src < me, _vis, lambda args: args, (dq, dk_acc, dv_acc)
            )
        else:
            dq_i, dk_i, dv_i = _chunk_bwd(
                q, k_cur, v_cur, out, lse, do, False, scale, interpret
            )
            dq += dq_i
            dk_acc += dk_i
            dv_acc += dv_i
        # accumulators rotate every step (incl. the last) to complete the
        # full ring and land back on the owning rank; k/v are not needed
        # after the last compute
        if i + 1 < n:
            k_cur = _rot(k_cur, axis_name, n)
            v_cur = _rot(v_cur, axis_name, n)
        dk_acc = _rot(dk_acc, axis_name, n)
        dv_acc = _rot(dv_acc, axis_name, n)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


_ring_flash_local.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


# the Pallas path needs whole q/k tiles — otherwise _flash_forward would
# silently fall back to dense WITHOUT lse, which the ring merge needs; the
# rule lives in pallas_kernels.flash_tiles_ok. (Head dim needs no gate:
# Mosaic pads sub-lane dims, verified on-chip down to d=8.)
_flash_tiles_ok = pk.flash_tiles_ok


def ring_attention_sharded(
    q, k, v, mesh, axis_name="sp", causal=False, scale=None, use_flash=None
):
    """q,k,v: (b, h, t, d) GLOBAL arrays (sharded or shardable on t over
    `axis_name`). Returns attention output with the same sharding.

    use_flash: None = auto (Pallas ring when tile shapes allow), True/False
    to force. The dense tier remains for ragged shards."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n_sp = mesh.shape[axis_name]
    if q.shape[2] % n_sp:
        raise ValueError(
            "sequence length %d not divisible by the %r axis size %d"
            % (q.shape[2], axis_name, n_sp)
        )
    t_loc = q.shape[2] // n_sp
    if use_flash is None:
        use_flash = _flash_tiles_ok(t_loc)
    elif use_flash and not _flash_tiles_ok(t_loc):
        raise ValueError(
            "flash ring needs t_local %% block == 0 (t_local=%d); "
            "pass use_flash=False for the dense ring" % t_loc
        )
    # batch rides the dp axis when the mesh has one (degrade gracefully on
    # sp-only meshes, matching sharded_embedding_lookup's guard)
    batch_axes = ("dp",) if "dp" in mesh.shape else None
    spec = P(batch_axes, None, (axis_name,), None)
    if use_flash:
        # shared defaulting rule with the flash kernels (fwd/bwd must agree)
        scale, interpret = pk._resolve_defaults(q, scale, None)

        # positional call: custom_vjp nondiff_argnums are position-based
        def local(q, k, v):
            return _ring_flash_local(
                q, k, v, axis_name, causal, scale, interpret
            )
    else:
        local = functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
        )
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # flash tier only: pallas_call out_shapes carry no varying-mesh-axes
        # annotation, which the replication checker requires; collective
        # correctness there is covered by the ring-vs-dense forward/grad
        # tests. The dense tier keeps the checker.
        **{SHARD_MAP_CHECK_KW: not use_flash},
    )
    return fn(q, k, v)


def ring_attention(q, k, v, causal=False, scale=None, axis_name="sp", mesh=None):
    """Plain attention when no sp sharding is active; ring algorithm when a
    mesh with a >1 'sp' axis is supplied (or found on the inputs)."""
    if mesh is not None and mesh.shape.get(axis_name, 1) > 1:
        return ring_attention_sharded(q, k, v, mesh, axis_name, causal, scale)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
