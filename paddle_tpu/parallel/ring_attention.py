"""Ring attention: exact attention over sequences sharded across the 'sp'
mesh axis (context parallelism over ICI).

The reference (2018-era) has NO sequence parallelism — its long-sequence
answer was LoD ragged batching (SURVEY.md §5.7); this is the new capability
the TPU build adds. Algorithm (Liu et al. ring attention; public pattern):
each rank holds a (b, h, t_local, d) shard of Q/K/V along the sequence; K/V
chunks rotate around the ring via ppermute while each rank accumulates its
queries' attention with an online (streaming) softmax — max/denominator
corrections per incoming chunk — so the result is EXACT full attention
without ever materializing the (t, t) score matrix on one chip, and the
K/V transfer overlaps compute around the ring.

Causal masking uses global positions derived from each chunk's rank of
origin (after i rotations a rank holds the chunk of rank (me - i) mod n).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]

NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name, causal, scale):
    """Runs inside shard_map: q,k,v are local (b, h, t_loc, d) shards."""
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape

    q_pos = me * t_loc + jnp.arange(t_loc)  # global positions of my queries

    def step(i, carry):
        k_cur, v_cur, m, l, o = carry
        src = (me - i) % n  # rank of origin of the chunk I currently hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new)

    m0 = jnp.full((b, h, t_loc), NEG_INF, q.dtype)
    l0 = jnp.zeros((b, h, t_loc), q.dtype)
    o0 = jnp.zeros((b, h, t_loc, d), q.dtype)
    carry = (k, v, m0, l0, o0)
    # unrolled python loop: n is a static mesh size, so XLA can pipeline the
    # ppermute of chunk i+1 behind the matmuls of chunk i
    for i in range(n):
        carry = step(i, carry)
    _, _, m, l, o = carry
    return o / jnp.maximum(l, 1e-20)[..., None]


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False, scale=None):
    """q,k,v: (b, h, t, d) GLOBAL arrays (sharded or shardable on t over
    `axis_name`). Returns attention output with the same sharding."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # batch rides the dp axis when the mesh has one (degrade gracefully on
    # sp-only meshes, matching sharded_embedding_lookup's guard)
    batch_axes = ("dp",) if "dp" in mesh.shape else None
    spec = P(batch_axes, None, (axis_name,), None)
    fn = jax.shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def ring_attention(q, k, v, causal=False, scale=None, axis_name="sp", mesh=None):
    """Plain attention when no sp sharding is active; ring algorithm when a
    mesh with a >1 'sp' axis is supplied (or found on the inputs)."""
    if mesh is not None and mesh.shape.get(axis_name, 1) > 1:
        return ring_attention_sharded(q, k, v, mesh, axis_name, causal, scale)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
