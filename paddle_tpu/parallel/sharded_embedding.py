"""Row-sharded embedding lookup (embedding parallelism).

Reference analog: the distributed lookup table (SURVEY.md §2.7.5) — a
high-dimensional embedding sharded across parameter servers, rows fetched by
RPC prefetch (distributed/parameter_prefetch.cc:26) and gradients pushed as
SelectedRows. TPU-native redesign: the table is row-sharded over a mesh axis;
each rank gathers its local hits (out-of-range ids produce zeros) and a psum
over the axis combines them — one ICI collective instead of an RPC round trip,
and the backward pass is the mirrored scatter-add that GSPMD derives
automatically from this forward.
"""

import functools

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import shard_map

__all__ = ["sharded_embedding_lookup"]


def _local_lookup(table_shard, ids, axis_name):
    """table_shard: (rows_local, d); ids: global int ids, any shape."""
    rows_local = table_shard.shape[0]
    me = lax.axis_index(axis_name)
    offset = me * rows_local
    local = ids - offset
    in_range = (local >= 0) & (local < rows_local)
    safe = jnp.clip(local, 0, rows_local - 1)
    picked = jnp.take(table_shard, safe.reshape(-1), axis=0)
    picked = jnp.where(in_range.reshape(-1)[:, None], picked, 0.0)
    out = picked.reshape(ids.shape + (table_shard.shape[1],))
    return lax.psum(out, axis_name)


def sharded_embedding_lookup(table, ids, mesh, axis_name="ep"):
    """table: (rows, d) global array sharded on rows over `axis_name`;
    ids: int array whose leading dim is the batch — kept sharded over 'dp'
    (when the mesh has it) so per-device work scales with batch/dp, not the
    global batch. Returns (ids.shape..., d) with the same dp sharding."""
    batch_spec = P(("dp",)) if "dp" in mesh.shape else P()
    fn = shard_map(
        functools.partial(_local_lookup, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P((axis_name,), None), batch_spec),
        out_specs=batch_spec,
    )
    return fn(table, ids)
