"""Back-compat shim: the row-sharded lookup grew into the sparse embedding
engine (paddle_tpu/embedding/ — EmbeddingEngine, SelectedRows gradients,
per-row optimizer updates). The kernel itself now lives in
embedding/lookup.py with dense-matching dtype/padding_idx/negative-id
semantics; import from there (or use layers.distributed_embedding /
embedding.EmbeddingEngine) in new code."""

from ..embedding.lookup import _local_lookup, sharded_embedding_lookup  # noqa: F401

__all__ = ["sharded_embedding_lookup"]
