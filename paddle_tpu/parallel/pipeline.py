"""Pipeline parallelism (GPipe microbatch schedule) over a mesh 'pp' axis.

The reference era (Fluid ~1.2) scaled across stages only via the pserver
graph split (transpiler/distribute_transpiler.py splits the program at
send/recv ops — reference paddle/fluid/transpiler); modern large-model
practice pipelines LAYER STAGES. TPU-native design, per the scaling-book
recipe rather than a send/recv port:

- stages are HOMOGENEOUS (a stack of identical blocks — the transformer
  case); their parameters are stacked on a leading [n_stages, ...] axis and
  sharded over the mesh's 'pp' axis, so each pp rank holds
  n_stages/pp_size consecutive stages;
- activations flow rank -> rank+1 through `lax.ppermute` (ICI
  neighbor-exchange, the NCCL-send/recv analog) on a GPipe schedule:
  microbatch m occupies rank r at tick m + r; the bubble is the classic
  (pp_size - 1) / (n_micro + pp_size - 1) fraction;
- the whole schedule is a traced loop of static length
  n_micro + pp_size - 1 inside ONE shard_map region, so XLA sees the
  compute/ppermute dependence chain and overlaps neighbor DMA with the
  next microbatch's stage compute;
- `ppermute` has a transpose rule, so `jax.grad` through the pipeline IS
  the backward pipeline (cotangents flow rank+1 -> rank via the reversed
  ring) — the GPipe schedules need no hand-written backward, and the
  optimizer update composes outside like any other jax.grad.

Two generations of schedule live here:

- the HOMOGENEOUS tier (`gpipe`/`gpipe_spmd`): stages are a stack of
  identical blocks, parameters stacked [n_stages, ...] and sharded P('pp');
- the HETEROGENEOUS tier (`pipeline_fwd_spmd`/`pipeline_1f1b_spmd`), the
  engine under ParallelExecutor's Program lowering: each pp rank holds ONE
  stage's arbitrary op subgraph (dispatched per-rank via lax.switch in the
  caller-built `stage_f`), activations cross stages through a uniform
  packed [mb, K] boundary buffer, and the 1F1B variant (PipeDream /
  Megatron flavor: Narayanan et al.) interleaves one forward with one
  backward per tick, rematerializing the stage forward at backward time so
  the stash holds only the O(pp) in-flight stage INPUTS instead of GPipe's
  O(n_micro) residual sets.

Composition: 'pp' is one axis of the SAME mesh as dp/tp/sp/ep, so a
dp2xpp4 mesh runs data-parallel pipelines (each dp slice pipelines its
own batch shard; parameter gradients psum over 'dp' at the optimizer like
every other ParallelExecutor path).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

# version-portable shard_map + replication-check kwarg spelling (the shim
# moved to collectives so every shard_map user in the package shares it)
from .collectives import SHARD_MAP_CHECK_KW as _CHECK_KW, axis_size, shard_map

__all__ = [
    "gpipe",
    "gpipe_spmd",
    "pipeline_fwd_spmd",
    "pipeline_1f1b_spmd",
    "analytic_bubble",
]

# (pp-1)/(m+pp-1), the fill-drain bound both schedules share. Canonical home
# is observability.stepstats (no jax dependency) so the telemetry layer can
# publish the analytic gauge next to its runtime two-m-slope measurement;
# re-exported here because this module owns the schedules it describes.
from ..observability.stepstats import analytic_bubble  # noqa: E402


def _apply_stages(stage_fn, params_local, x):
    """Chain this rank's consecutive stages (leading axis of params_local)."""

    def body(carry, p):
        return stage_fn(p, carry), None

    out, _ = lax.scan(body, x, params_local)
    return out


def gpipe_spmd(stage_fn, params_local, x, n_micro, axis_name="pp"):
    """The per-shard GPipe schedule — call INSIDE an existing shard_map
    whose mesh has `axis_name`. `params_local` is this rank's
    [n_local, ...] stage stack; `x` is the (already dp-sharded) batch,
    replicated across `axis_name`. Returns the last stage's outputs,
    replicated across `axis_name`."""
    pp = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_micro))
    mb = b // n_micro
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])

    ticks = n_micro + pp - 1
    recv = jnp.zeros_like(
        jax.eval_shape(lambda p, v: _apply_stages(stage_fn, p, v),
                       params_local, x_micro[0]),
    )
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    outs = []
    for t in range(ticks):
        # rank 0 injects microbatch t (clamped: past the last microbatch it
        # reprocesses garbage whose outputs are never collected); other
        # ranks consume the neighbor's activation from tick t-1
        inj = x_micro[min(t, n_micro - 1)]
        inp = jnp.where(r == 0, inj.astype(recv.dtype), recv)
        out = _apply_stages(stage_fn, params_local, inp)
        recv = lax.ppermute(out, axis_name, perm)
        outs.append(out)

    # the LAST rank's outputs at ticks pp-1 .. pp-1+n_micro-1 are the
    # pipeline's results for microbatches 0..n_micro-1; replicate them to
    # every pp rank with a masked psum (its transpose routes cotangents
    # back to the last rank — the backward pipeline's entry point)
    y = jnp.stack(outs[pp - 1 : pp - 1 + n_micro])
    y = lax.psum(jnp.where(r == pp - 1, y, jnp.zeros_like(y)), axis_name)
    return y.reshape((b,) + y.shape[2:])


def gpipe(stage_fn, stacked_params, x, n_micro, mesh, axis_name="pp",
          batch_axis="dp"):
    """Run a stack of homogeneous stages as a GPipe pipeline over
    `mesh`'s `axis_name`, data-parallel over `batch_axis`.

    stage_fn(params_i, x) -> y with y.shape == x.shape (homogeneous
    stages); stacked_params: pytree with leading axis n_stages (must be
    divisible by the pp size); x: [batch, ...] global batch. Returns the
    final stage outputs [batch, ...]. Differentiable end to end.
    """
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    pp = mesh.shape[axis_name]
    if n_stages % pp:
        raise ValueError("%d stages not divisible over pp=%d" % (n_stages, pp))

    fn = shard_map(
        functools.partial(gpipe_spmd, stage_fn, n_micro=n_micro,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P(batch_axis)),
        out_specs=P(batch_axis),
        **{_CHECK_KW: False},
    )
    params_sh = jax.device_put(
        stacked_params, NamedSharding(mesh, P(axis_name))
    )
    x_sh = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(batch_axis)))
    return fn(params_sh, x_sh)


# ---------------------------------------------------------------------------
# Heterogeneous-stage engines (the ParallelExecutor Program lowering's core)
# ---------------------------------------------------------------------------
#
# Caller contract (both engines; call INSIDE a shard_map whose mesh binds
# `axis_name`): `stage_f(boundary_in, mb_idx) -> (boundary_out, scalars)`
# runs THIS RANK's stage (dispatch over lax.axis_index inside, e.g. via
# lax.switch) on one microbatch. boundary_in/out are the uniform packed
# activation buffers [mb, K] float32; `scalars` is a packed [n_scalars]
# float32 vector that only the LAST stage fills (loss + scalar fetches);
# mb_idx is the (traced, clamped-valid) microbatch index for feed slicing.
# Microbatch-MEAN combination: the engines average scalars over microbatches
# (exact for batch-mean losses/metrics when n_micro divides the batch).


def pipeline_fwd_spmd(stage_f, n_micro, boundary_shape, n_scalars,
                      axis_name="pp"):
    """GPipe forward schedule over heterogeneous stages: microbatch m
    occupies rank r at tick m + r; ticks = n_micro + pp - 1; the bubble is
    (pp-1)/(n_micro+pp-1). Returns the microbatch-mean scalars vector,
    replicated over `axis_name`. Backward: differentiate THROUGH this
    function (ppermute/psum transposes give the reversed-ring cotangent
    pipeline); peak liveness is the classic GPipe O(n_micro) residual set."""
    pp = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    recv = jnp.zeros(boundary_shape, jnp.float32)
    scal_sum = jnp.zeros((n_scalars,), jnp.float32)
    for t in range(n_micro + pp - 1):
        f = t - r  # microbatch this rank works on at tick t (may be invalid)
        fvalid = (f >= 0) & (f < n_micro)
        fc = jnp.clip(f, 0, n_micro - 1)
        out, scal = stage_f(recv, fc)
        scal_sum = scal_sum + jnp.where(
            fvalid & (r == pp - 1), scal, jnp.zeros_like(scal)
        )
        recv = lax.ppermute(out, axis_name, perm)
    scal_mean = scal_sum / n_micro
    return lax.psum(
        jnp.where(r == pp - 1, scal_mean, jnp.zeros_like(scal_mean)), axis_name
    )


def pipeline_1f1b_spmd(stage_f, params_local, n_micro, boundary_shape,
                       scal_cotangent, axis_name="pp"):
    """1F1B schedule (PipeDream-flush / Megatron): each tick interleaves one
    forward with one backward sub-step, so microbatch b's backward at rank r
    runs at tick b + 2(pp-1) - r — in-flight forwards per rank stay at most
    2(pp-1-r)+1 ≈ O(pp) instead of GPipe's O(n_micro). The backward is
    hand-scheduled with per-stage jax.vjp, REMATERIALIZING the stage forward
    from the stashed boundary input (activation-checkpoint flavor), so the
    stash is a [2·pp, mb, K] ring buffer of stage inputs, not full residuals.

    `stage_f(params_local, boundary_in, mb_idx) -> (boundary_out, scalars)`
    (params explicit here so vjp can differentiate w.r.t. them).
    `scal_cotangent` [n_scalars] seeds the loss cotangent at the LAST rank
    (one-hot at the loss slot, scaled 1/n_micro for the microbatch mean).

    Returns (microbatch-mean scalars replicated over axis_name,
    accumulated parameter-buffer gradient shaped like params_local).
    The math is identical to GPipe's jax.grad — same per-microbatch grads,
    summed — only the schedule (and liveness) differs.
    """
    pp = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    perm_f = [(i, (i + 1) % pp) for i in range(pp)]
    perm_b = [(i, (i - 1) % pp) for i in range(pp)]
    n_scalars = scal_cotangent.shape[0]

    # stash of in-flight stage INPUTS keyed f mod W, one trash slot at W for
    # invalid-tick writes (clobbering a live slot would corrupt the replay)
    W = 2 * pp - 1
    stash = jnp.zeros((W + 1,) + tuple(boundary_shape), jnp.float32)
    gacc = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params_local
    )
    scal_sum = jnp.zeros((n_scalars,), jnp.float32)
    recv_f = jnp.zeros(boundary_shape, jnp.float32)
    recv_b = jnp.zeros(boundary_shape, jnp.float32)

    for t in range(n_micro + 2 * (pp - 1)):
        # ---- forward sub-step: microbatch f = t - r
        f = t - r
        fvalid = (f >= 0) & (f < n_micro)
        fc = jnp.clip(f, 0, n_micro - 1)
        out_f, scal = stage_f(params_local, recv_f, fc)
        scal_sum = scal_sum + jnp.where(
            fvalid & (r == pp - 1), scal, jnp.zeros_like(scal)
        )
        slot = jnp.where(fvalid, jnp.remainder(fc, W), W)
        stash = lax.dynamic_update_index_in_dim(
            stash, recv_f[None], slot, axis=0
        )
        recv_f = lax.ppermute(out_f, axis_name, perm_f)

        # ---- backward sub-step: microbatch b = t - 2(pp-1) + r
        b = t - 2 * (pp - 1) + r
        bvalid = (b >= 0) & (b < n_micro)
        bc = jnp.clip(b, 0, n_micro - 1)
        bin_b = lax.dynamic_index_in_dim(
            stash, jnp.remainder(bc, W), axis=0, keepdims=False
        )
        _, vjp = jax.vjp(
            lambda p, bi: stage_f(p, bi, bc), params_local, bin_b
        )
        is_last = r == pp - 1
        cot_out = jnp.where(is_last, jnp.zeros_like(recv_b), recv_b)
        cot_scal = jnp.where(
            is_last, scal_cotangent, jnp.zeros_like(scal_cotangent)
        )
        gp, gbi = vjp((cot_out, cot_scal))
        gacc = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(bvalid, g, jnp.zeros_like(g)),
            gacc, gp,
        )
        send = jnp.where(bvalid, gbi, jnp.zeros_like(gbi))
        recv_b = lax.ppermute(send, axis_name, perm_b)

    scal_mean = scal_sum / n_micro
    scal_repl = lax.psum(
        jnp.where(r == pp - 1, scal_mean, jnp.zeros_like(scal_mean)), axis_name
    )
    return scal_repl, gacc
