"""Pipeline parallelism (GPipe microbatch schedule) over a mesh 'pp' axis.

The reference era (Fluid ~1.2) scaled across stages only via the pserver
graph split (transpiler/distribute_transpiler.py splits the program at
send/recv ops — reference paddle/fluid/transpiler); modern large-model
practice pipelines LAYER STAGES. TPU-native design, per the scaling-book
recipe rather than a send/recv port:

- stages are HOMOGENEOUS (a stack of identical blocks — the transformer
  case); their parameters are stacked on a leading [n_stages, ...] axis and
  sharded over the mesh's 'pp' axis, so each pp rank holds
  n_stages/pp_size consecutive stages;
- activations flow rank -> rank+1 through `lax.ppermute` (ICI
  neighbor-exchange, the NCCL-send/recv analog) on a GPipe schedule:
  microbatch m occupies rank r at tick m + r; the bubble is the classic
  (pp_size - 1) / (n_micro + pp_size - 1) fraction;
- the whole schedule is a traced loop of static length
  n_micro + pp_size - 1 inside ONE shard_map region, so XLA sees the
  compute/ppermute dependence chain and overlaps neighbor DMA with the
  next microbatch's stage compute;
- `ppermute` has a transpose rule, so `jax.grad` through the pipeline IS
  the backward pipeline (cotangents flow rank+1 -> rank via the reversed
  ring) — no hand-written 1F1B machinery, and the optimizer update
  composes outside like any other jax.grad.

Composition: 'pp' is one axis of the SAME mesh as dp/tp/sp/ep, so a
dp2xpp4 mesh runs data-parallel pipelines (each dp slice pipelines its
own batch shard; parameter gradients psum over 'dp' at the optimizer like
every other ParallelExecutor path).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

# version-portable shard_map + replication-check kwarg spelling (the shim
# moved to collectives so every shard_map user in the package shares it)
from .collectives import SHARD_MAP_CHECK_KW as _CHECK_KW, axis_size, shard_map

__all__ = ["gpipe", "gpipe_spmd"]


def _apply_stages(stage_fn, params_local, x):
    """Chain this rank's consecutive stages (leading axis of params_local)."""

    def body(carry, p):
        return stage_fn(p, carry), None

    out, _ = lax.scan(body, x, params_local)
    return out


def gpipe_spmd(stage_fn, params_local, x, n_micro, axis_name="pp"):
    """The per-shard GPipe schedule — call INSIDE an existing shard_map
    whose mesh has `axis_name`. `params_local` is this rank's
    [n_local, ...] stage stack; `x` is the (already dp-sharded) batch,
    replicated across `axis_name`. Returns the last stage's outputs,
    replicated across `axis_name`."""
    pp = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_micro))
    mb = b // n_micro
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])

    ticks = n_micro + pp - 1
    recv = jnp.zeros_like(
        jax.eval_shape(lambda p, v: _apply_stages(stage_fn, p, v),
                       params_local, x_micro[0]),
    )
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    outs = []
    for t in range(ticks):
        # rank 0 injects microbatch t (clamped: past the last microbatch it
        # reprocesses garbage whose outputs are never collected); other
        # ranks consume the neighbor's activation from tick t-1
        inj = x_micro[min(t, n_micro - 1)]
        inp = jnp.where(r == 0, inj.astype(recv.dtype), recv)
        out = _apply_stages(stage_fn, params_local, inp)
        recv = lax.ppermute(out, axis_name, perm)
        outs.append(out)

    # the LAST rank's outputs at ticks pp-1 .. pp-1+n_micro-1 are the
    # pipeline's results for microbatches 0..n_micro-1; replicate them to
    # every pp rank with a masked psum (its transpose routes cotangents
    # back to the last rank — the backward pipeline's entry point)
    y = jnp.stack(outs[pp - 1 : pp - 1 + n_micro])
    y = lax.psum(jnp.where(r == pp - 1, y, jnp.zeros_like(y)), axis_name)
    return y.reshape((b,) + y.shape[2:])


def gpipe(stage_fn, stacked_params, x, n_micro, mesh, axis_name="pp",
          batch_axis="dp"):
    """Run a stack of homogeneous stages as a GPipe pipeline over
    `mesh`'s `axis_name`, data-parallel over `batch_axis`.

    stage_fn(params_i, x) -> y with y.shape == x.shape (homogeneous
    stages); stacked_params: pytree with leading axis n_stages (must be
    divisible by the pp size); x: [batch, ...] global batch. Returns the
    final stage outputs [batch, ...]. Differentiable end to end.
    """
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    pp = mesh.shape[axis_name]
    if n_stages % pp:
        raise ValueError("%d stages not divisible over pp=%d" % (n_stages, pp))

    fn = shard_map(
        functools.partial(gpipe_spmd, stage_fn, n_micro=n_micro,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P(batch_axis)),
        out_specs=P(batch_axis),
        **{_CHECK_KW: False},
    )
    params_sh = jax.device_put(
        stacked_params, NamedSharding(mesh, P(axis_name))
    )
    x_sh = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(batch_axis)))
    return fn(params_sh, x_sh)
