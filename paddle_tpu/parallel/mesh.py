"""Mesh construction (the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives). Axis order puts dp outermost so data
parallel rides DCN across hosts while tp/sp ride ICI within a slice."""

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["MeshConfig", "make_mesh"]


class MeshConfig:
    """Named mesh-axis sizes. size=-1 on one axis means 'all remaining
    devices'."""

    def __init__(self, dp=-1, fsdp=1, tp=1, sp=1, ep=1, pp=1):
        self.axes = {
            "dp": dp, "fsdp": fsdp, "tp": tp, "sp": sp, "ep": ep, "pp": pp
        }

    def resolve(self, n_devices):
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    "%d devices not divisible by fixed axes %s" % (n_devices, sizes)
                )
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(
                "mesh %s needs %d devices, have %d" % (sizes, total, n_devices)
            )
        return sizes


def make_mesh(config=None, devices=None):
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    names = [k for k in ("dp", "fsdp", "tp", "sp", "ep", "pp")]
    shape = [sizes[k] for k in names]
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(names))
