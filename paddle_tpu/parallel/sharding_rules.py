"""Declarative sharding rules: param/activation names -> PartitionSpecs.

The single source of truth for tensor placement over the dp×fsdp×tp×sp×ep×pp
mesh. Before this engine, sharding was hand-placed per feature — ZeRO-1
constraints inside core_ops._opt_f32, the embedding `ep` spec inside
embedding/engine.py — which could not express tensor parallelism or FSDP at
all. Rules replace both with one mechanism (exemplars: EasyLM's
match_partition_rules regex table and MaxText's SpecLayout canonical
per-role layouts):

- `ShardingRules` holds ordered (regex, spec) pairs. A name resolves by
  re.search against every rule, LAST match wins (append more-specific rules
  after catch-alls). Unmatched names stay replicated. Specs follow the
  sharding_spec tuple convention: one entry per dim, each None | axis name |
  tuple of axis names, e.g. ("fsdp", "tp") or (("fsdp", "tp"), None).
- `SpecLayout` names the canonical layouts for the transformer roles
  (embedding / column-parallel / row-parallel / vector) so model code asks
  for intents, not axis tuples.
- `Resolver` binds rules to a live mesh + lowered block: prunes axes the
  mesh doesn't have, degrades non-divisible dims to replication, aliases
  optimizer accumulators to their parameter's layout, and layers the legacy
  `Variable.sharding_spec` attribute (parallel.shard_parameter) and the
  ZeRO-1 state tier underneath explicit rules. The executor consults it at
  its one placement choke point (state in/out_shardings + op-output
  constraints), so the same program runs on ANY mesh — axes it lacks simply
  prune away.

Wire behavior falls out of GSPMD (docs/parallelism.md): an fsdp rule on a
parameter makes its use all-gather and its gradient combine reduce-scatter
(FSDP); a ("fsdp","tp")/("tp","fsdp") column/row pair on a matmul pair
makes the partitioner place the tp all-reduce after the second matmul
(Megatron TP). tools/comm_audit.py cross-checks both against analytic ring
formulas.
"""

import re

import numpy as np

__all__ = [
    "MESH_AXES",
    "ShardingRules",
    "SpecLayout",
    "program_rules",
    "Resolver",
    "opt_constrain_ins",
    "opt_constrain_outs",
]

# the canonical mesh axes (parallel.mesh.MeshConfig order). Rules may only
# name these; anything else is a typo caught at add() time, not a silent
# replication at run time.
MESH_AXES = ("dp", "fsdp", "tp", "sp", "ep", "pp")


def _normalize_spec(spec):
    """Canonicalize one spec tuple: each dim entry None | axis | tuple of
    axes. Returns a hashable nested tuple; raises ValueError on unknown
    axis names or malformed entries."""
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        for a in axes:
            if a not in MESH_AXES:
                raise ValueError(
                    "unknown mesh axis %r in sharding spec %r (valid: %s)"
                    % (a, tuple(spec), ", ".join(MESH_AXES))
                )
        if len(set(axes)) != len(axes):
            raise ValueError("repeated axis in sharding spec entry %r" % (entry,))
        out.append(tuple(axes) if len(axes) > 1 else axes[0])
    return tuple(out)


class ShardingRules:
    """Ordered (regex, PartitionSpec-tuple) rules, LAST match wins.

    Matching uses re.search (a bare parameter name matches anywhere in the
    var name — anchor with ^…$ when that is too loose; note an unanchored
    pattern also matches derived names like `<param>@GRAD` and the
    `<param>_<slot>_acc_<k>` accumulators, which is usually what you want
    for a storage layout). `add` validates axis names eagerly and returns
    self for chaining."""

    def __init__(self, rules=()):
        self._rules = []  # [(pattern str, compiled, spec)]
        for pattern, spec in rules:
            self.add(pattern, spec)

    def add(self, pattern, spec):
        self._rules.append((pattern, re.compile(pattern), _normalize_spec(spec)))
        return self

    def extend(self, other):
        """Append another rule set's rules after this one's (so `other`
        wins ties under last-match)."""
        if other is not None:
            for pattern, _, spec in other._rules:
                self._rules.append((pattern, re.compile(pattern), spec))
        return self

    def match(self, name):
        """Resolved spec tuple for `name`, or None (replicated) when no rule
        matches. A matching rule with spec None explicitly forces
        replication (useful to exempt names from an earlier catch-all)."""
        found = None
        for _, rx, spec in self._rules:
            if rx.search(name):
                found = (spec,)
        return found[0] if found is not None else None

    def fingerprint(self):
        """Hashable identity for executor compile-cache keys: rules are
        attached to live Program objects and may grow after a first run."""
        return tuple((p, s) for p, _, s in self._rules)

    def __len__(self):
        return len(self._rules)

    def __iter__(self):
        for pattern, _, spec in self._rules:
            yield pattern, spec

    def __repr__(self):
        return "ShardingRules(%r)" % (list(self),)


class SpecLayout:
    """Canonical per-role layouts over the standard axes — the MaxText-style
    vocabulary model code uses instead of hand-written axis tuples.

    Roles (2-D weights are [in_features, out_features], fluid convention):

    - embedding():        ((fsdp, tp), None) — vocab rows split over both
                          model axes, feature dim whole.
    - column_parallel():  (fsdp, tp)  — qkv / ffn-up: out-features over tp
                          (per-head shards), in-features over fsdp.
    - row_parallel():     (tp, fsdp)  — attn-out / ffn-down: in-features
                          over tp so the pair's reduce lands HERE (GSPMD
                          places one tp all-reduce after the second matmul).
    - vector():           (fsdp,)     — biases / norm scales: fsdp only
                          (tp-sharding rank-1 state buys nothing).
    """

    def __init__(self, fsdp_axis="fsdp", tp_axis="tp", ep_axis="ep"):
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis
        self.ep_axis = ep_axis

    def embedding(self):
        return ((self.fsdp_axis, self.tp_axis), None)

    def column_parallel(self):
        return (self.fsdp_axis, self.tp_axis)

    def row_parallel(self):
        return (self.tp_axis, self.fsdp_axis)

    def vector(self):
        return (self.fsdp_axis,)

    def transformer_rules(self, column=(), row=(), vector=(), embedding=()):
        """Build a ShardingRules from name patterns per role (the common
        case: one call listing the model's weight-name regexes)."""
        rules = ShardingRules()
        for pat in embedding:
            rules.add(pat, self.embedding())
        for pat in column:
            rules.add(pat, self.column_parallel())
        for pat in row:
            rules.add(pat, self.row_parallel())
        for pat in vector:
            rules.add(pat, self.vector())
        return rules


def program_rules(program):
    """The ShardingRules attached to `program`, created on first use.
    Model-building code (embedding engine, user layers) registers storage
    layouts here; ParallelExecutor merges them with
    BuildStrategy.sharding_rules (build-strategy rules win ties) and the
    pass pipeline carries them across program rewrites."""
    rules = getattr(program, "_sharding_rules", None)
    if rules is None:
        rules = ShardingRules()
        program._sharding_rules = rules
    return rules


class Resolver:
    """Rules bound to a live mesh: name -> pruned spec / NamedSharding.

    Precedence per name (first hit wins):
      1. explicit rules (program rules + BuildStrategy rules, last match
         wins within the combined list);
      2. accumulator alias: optimizer-state tensors (ZERO1_STATE_SLOTS)
         resolve through their parameter's name, so moments always inherit
         the param's storage layout without name-pattern gymnastics;
      3. the legacy `Variable.sharding_spec` attribute
         (parallel.shard_parameter);
      4. ZeRO-1 state names (set by the executor) -> (zero1_axis,);
      5. replicated.

    Pruning makes any program runnable on any mesh: axes the mesh lacks (or
    has at extent 1) drop out; a dim whose size doesn't divide its axes'
    combined extent degrades to replication for that dim; a spec longer
    than the value's rank resolves to replicated. All-None specs collapse
    to None so callers can treat None as 'no placement opinion'."""

    def __init__(self, mesh, rules=None, var_lookup=None):
        self.mesh = mesh
        self.rules = rules if rules is not None and len(rules) else None
        self._var_lookup = var_lookup  # name -> Variable or None (legacy attr)
        self.aliases = {}  # state/accumulator name -> param name
        self.zero1_axis = None
        self.zero1_names = frozenset()
        # structured record of every divisibility degradation _prune applied
        # (was silent before the static analyzer landed): [(name, dim, axes,
        # dim_size, extent)], recorded once per (name, dim) and counted into
        # the observability registry (analysis/sharding_degraded). fluidlint's
        # sharding-rules checker reports the same condition statically.
        self.degraded = []
        self._degraded_seen = set()

    def set_zero1(self, axis, names):
        self.zero1_axis = axis
        self.zero1_names = frozenset(names)

    def add_aliases(self, ops):
        """Map every optimizer-state input (ZERO1_STATE_SLOTS) to its op's
        Param name so layer 2 can resolve accumulators."""
        from ..ops.core_ops import ZERO1_STATE_SLOTS

        for op in ops:
            slots = ZERO1_STATE_SLOTS.get(op.type)
            if not slots:
                continue
            params = op.inputs.get("Param", ())
            if not params:
                continue
            for slot in slots:
                for name in op.inputs.get(slot, ()):
                    self.aliases[name] = params[0]

    def _record_degraded(self, name, dim, axes, dim_size, extent):
        key = (name, dim)
        if name is None or key in self._degraded_seen:
            return
        self._degraded_seen.add(key)
        self.degraded.append((name, dim, axes, dim_size, extent))
        from ..observability import registry as _registry

        _registry.default_registry().counter(
            "analysis/sharding_degraded",
            "spec dims degraded to replication because the dim size did not "
            "divide the mesh-axes extent",
        ).inc(axes="+".join(axes))

    def _prune(self, spec, shape, name=None):
        if spec is None:
            return None
        shape = tuple(shape) if shape is not None else None
        if shape is not None and len(spec) > len(shape):
            return None
        out = []
        for dim, entry in enumerate(spec):
            axes = () if entry is None else (
                tuple(entry) if isinstance(entry, tuple) else (entry,)
            )
            kept = tuple(a for a in axes if self.mesh.shape.get(a, 1) > 1)
            if kept and shape is not None:
                extent = int(np.prod([self.mesh.shape[a] for a in kept]))
                if shape[dim] % extent != 0:
                    self._record_degraded(name, dim, kept, shape[dim], extent)
                    kept = ()
            out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        if all(e is None for e in out):
            return None
        return tuple(out)

    def rule_spec(self, name, shape=None):
        """Layers 1-3 only (explicit rules / alias / legacy attr), pruned to
        this mesh. The layer the ZeRO-1 tier defers to: a param whose rule
        survives pruning leaves the zero1 path entirely."""
        raw = None
        if self.rules is not None:
            raw = self.rules.match(name)
            if raw is None and name in self.aliases:
                raw = self.rules.match(self.aliases[name])
        if raw is None and self._var_lookup is not None:
            v = self._var_lookup(name)
            if v is None and name in self.aliases:
                v = self._var_lookup(self.aliases[name])
            spec = getattr(v, "sharding_spec", None)
            if spec is not None:
                raw = _normalize_spec(spec)
        return self._prune(raw, shape, name=name)

    def audit(self, names):
        """Dead-rule audit: patterns matching none of `names` (typically the
        lowered block's vars plus the scope's persistables) are typos or
        stale layouts silently replicating their target. Returns the dead
        pattern list and counts each into the observability registry
        (analysis/sharding_dead_rules); the executor runs this once per
        compile, fluidlint's sharding-rules checker statically."""
        if self.rules is None:
            return []
        names = list(names)
        dead = []
        for pattern, rx, _ in self.rules._rules:
            if not any(rx.search(n) for n in names):
                dead.append(pattern)
        if dead:
            from ..observability import registry as _registry

            c = _registry.default_registry().counter(
                "analysis/sharding_dead_rules",
                "sharding rules whose pattern matched no var at compile",
            )
            for pattern in dead:
                c.inc(pattern=pattern)
        return dead

    def spec(self, name, shape=None):
        """Full precedence chain -> pruned spec tuple or None (replicated)."""
        s = self.rule_spec(name, shape)
        if s is not None:
            return s
        if name in self.zero1_names:
            return (self.zero1_axis,)
        return None

    def named_sharding(self, name, shape=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        s = self.spec(name, shape)
        return NamedSharding(self.mesh, P() if s is None else P(*s))

    def constrain(self, x, spec):
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(*spec))
        )

    def constrain_outputs(self, op, env):
        """The activation/placement hook _lower_one calls after binding an
        op's outputs: every output name with an explicit rule (layers 1-3)
        gets a with_sharding_constraint in its pruned layout. Idempotent on
        already-placed values; a no-op for unmatched names, so per-op cost
        is a few regex searches at trace time."""
        for name in op.output_arg_names:
            v = env.get(name)
            if v is None or not hasattr(v, "shape"):
                continue
            s = self.rule_spec(name, np.shape(v))
            if s is not None:
                env[name] = self.constrain(v, s)


# ---------------------------------------------------------------------------
# Optimizer-op constraints (the core_ops._opt_f32 seam)
# ---------------------------------------------------------------------------
# Both helpers are called by _opt_f32 around the f32 update math: ins BEFORE
# the upcast (the wire carries the grad's native dtype; the upcast then
# touches only the local shard), outs AFTER the downcast.


def _op_param_spec(ctx, ins):
    """The Param's storage spec from the rule engine (layers 1-3), or None.
    Identified via ctx.op (set by registry._lower_one); shape from the
    traced Param value, so pruning sees the real dims."""
    resolver = getattr(ctx, "sharding", None)
    op = getattr(ctx, "op", None)
    if resolver is None or op is None:
        return None
    params = op.inputs.get("Param", ())
    pvals = ins.get("Param", ())
    if not params or not pvals or pvals[0] is None:
        return None
    return resolver.rule_spec(params[0], np.shape(pvals[0]))


def _zero1_active(ctx):
    axis = getattr(ctx, "zero1_axis", None)
    mesh = getattr(ctx, "mesh", None)
    if axis and mesh is not None and mesh.shape.get(axis, 1) > 1:
        return mesh, axis
    return None, None


def opt_constrain_ins(ctx, ins):
    """Pin optimizer-op inputs to the parameter's storage layout.

    Rule-sharded param (FSDP / TP): every floating input WITH THE PARAM'S
    SHAPE (Param, Grad, moments) is constrained to the param's spec. On the
    gradient — still an unpositioned cross-replica partial sum here — GSPMD
    materializes the combine as reduce-scatter over the sharded dims (the
    FSDP grad path); on the param and moments it confirms the stored layout.
    Scalar state (LearningRate, Beta*Pow) never matches the shape and stays
    replicated.

    Otherwise, under the ZeRO-1 tier: every shardable floating input is
    pinned to a 1/dp shard along dim 0 — reduce-scatter on the grad, local
    slice on the replicated param, stored-layout no-op on the moments."""
    import jax.numpy as jnp

    from . import collectives as _coll

    pspec = _op_param_spec(ctx, ins)
    if pspec is not None:
        resolver = ctx.sharding
        pshape = np.shape(ins["Param"][0])
        out = {}
        for slot, vals in ins.items():
            cons = []
            for a in vals:
                if (
                    a is not None
                    and np.shape(a) == pshape
                    and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                ):
                    a = resolver.constrain(a, pspec)
                cons.append(a)
            out[slot] = cons
        return out

    mesh, axis = _zero1_active(ctx)
    if mesh is None:
        return ins
    out = {}
    for slot, vals in ins.items():
        cons = []
        for a in vals:
            if (
                a is not None
                and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                and _coll.zero1_shardable(jnp.shape(a), mesh, axis)
            ):
                a = _coll.constrain_sharded(a, mesh, axis)
            cons.append(a)
        out[slot] = cons
    return out


def opt_constrain_outs(ctx, res, ins):
    """Pin optimizer-op outputs to their storage layouts.

    Rule-sharded param: ParamOut and the moment outs stay IN the param's
    spec — under FSDP the param itself lives sharded (all-gather happens at
    next use, placed by GSPMD), so unlike ZeRO-1 there is no gather here.

    ZeRO-1 tier: ParamOut is constrained back to replicated (GSPMD -> the
    param all-gather, overlappable with the rest of the step) — but pinned
    to the sharded layout FIRST: without that the partitioner may push the
    replicated constraint through the update arithmetic and gather every
    operand separately (observed on the CPU partitioner: p and lr·v each
    all-gathered, 2x the wire bytes). Every other shardable state output
    (moments) stays sharded — the 1/dp state-memory and HBM-traffic win."""
    import jax.numpy as jnp

    from . import collectives as _coll

    pspec = _op_param_spec(ctx, ins)
    if pspec is not None:
        resolver = ctx.sharding
        pshape = np.shape(ins["Param"][0])
        out = {}
        for slot, vals in res.items():
            cons = []
            for v in vals:
                if (
                    v is not None
                    and np.shape(v) == pshape
                    and jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                ):
                    v = resolver.constrain(v, pspec)
                cons.append(v)
            out[slot] = cons
        return out

    mesh, axis = _zero1_active(ctx)
    if mesh is None:
        return res
    out = {}
    for slot, vals in res.items():
        cons = []
        for v in vals:
            if v is not None and jnp.issubdtype(
                jnp.asarray(v).dtype, jnp.floating
            ):
                if slot == "ParamOut":
                    if _coll.zero1_shardable(jnp.shape(v), mesh, axis):
                        v = _coll.constrain_sharded(v, mesh, axis)
                    v = _coll.constrain_replicated(v, mesh)
                elif _coll.zero1_shardable(jnp.shape(v), mesh, axis):
                    v = _coll.constrain_sharded(v, mesh, axis)
            cons.append(v)
        out[slot] = cons
    return out
