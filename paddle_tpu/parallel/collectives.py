"""Collective wrappers for shard_map code (reference: the NCCL op set —
all_reduce_op_handle.cc, reduce_op_handle.cc, broadcast_op_handle.cc —
and the legacy nccl ops). Inside shard_map these lower to XLA collectives
over ICI/DCN.

The second half are GSPMD-path equivalents: sharding CONSTRAINTS placed on
values inside a jit-over-Mesh trace (no shard_map region needed). GSPMD
materializes them as the matching collectives — constraining a cross-replica
partial sum to a sharded layout yields reduce-scatter, constraining a sharded
value back to replicated yields all-gather — which is how the ZeRO-1
optimizer tier (ReduceStrategy.Reduce, docs/parallelism.md) expresses
reduce-scatter(grad) → sharded update → all-gather(param) while leaving XLA
free to overlap the collectives with backward compute."""

import inspect

import jax
import jax.numpy as jnp
from jax import lax

try:  # newer jax exposes the function at jax.shard_map
    from jax import shard_map as _sm

    shard_map = _sm if callable(_sm) else _sm.shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

# the replication-check kwarg was renamed check_rep -> check_vma across jax
# versions; resolve once so callers can spell it portably:
# shard_map(f, ..., **{SHARD_MAP_CHECK_KW: False})
SHARD_MAP_CHECK_KW = (
    "check_rep"
    if "check_rep" in inspect.signature(shard_map).parameters
    else "check_vma"
)

__all__ = [
    "shard_map",
    "SHARD_MAP_CHECK_KW",
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "ppermute_shift",
    "broadcast",
    "axis_index",
    "axis_size",
    "constrain_sharded",
    "constrain_replicated",
    "zero1_shardable",
]


def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    raise ValueError("unknown reduce op %r" % op)


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_shift(x, axis_name, shift=1):
    """Rotate shards around the ring: each rank sends to rank+shift."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name, root=0):
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    """Static extent of a bound mesh axis. lax.axis_size is a late addition;
    on older jax, psum of the unit literal is the documented static-size
    spelling (constant-folded, no collective emitted)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


# ---------------------------------------------------------------------------
# GSPMD-path constraints (jit-over-Mesh traces, no shard_map)
# ---------------------------------------------------------------------------


def zero1_shardable(shape, mesh, axis_name):
    """True iff an array of `shape` can hold a 1/axis shard per rank: the
    leading dim divides evenly over the axis extent. Scalars and the shape-[1]
    optimizer scalars (LearningRate, Beta*Pow) are excluded by construction —
    they stay replicated, which keeps their update math identical to the
    all-reduce path."""
    n = mesh.shape.get(axis_name, 1)
    return n > 1 and len(shape) >= 1 and shape[0] % n == 0


def constrain_sharded(x, mesh, axis_name, dim=0):
    """Constrain `x` to be sharded over `axis_name` along `dim`. Applied to a
    cross-replica gradient partial sum, GSPMD lowers the combine as
    reduce-scatter ((p-1)/p · bytes on the wire) instead of all-reduce
    (2(p-1)/p); applied to replicated state it is a local slice."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * x.ndim
    spec[dim] = axis_name
    return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def constrain_replicated(x, mesh):
    """Constrain `x` to be fully replicated. Applied to a sharded updated
    parameter, GSPMD materializes the all-gather back to every rank."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return lax.with_sharding_constraint(x, NamedSharding(mesh, P()))
