"""Collective wrappers for shard_map code (reference: the NCCL op set —
all_reduce_op_handle.cc, reduce_op_handle.cc, broadcast_op_handle.cc —
and the legacy nccl ops). Inside shard_map these lower to XLA collectives
over ICI/DCN."""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "ppermute_shift",
    "broadcast",
    "axis_index",
    "axis_size",
]


def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    raise ValueError("unknown reduce op %r" % op)


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_shift(x, axis_name, shift=1):
    """Rotate shards around the ring: each rank sends to rank+shift."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name, root=0):
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.axis_size(axis_name)
