"""Program partitioner for the pp tier: cut a topologically-ordered op list
into N contiguous pipeline stages.

Two sources of the cut, mirroring the reference pipeline optimizer's split
(reference pipeline_trainer + device_guard sections) vs modern practice:

- EXPLICIT: ops carry `framework.PIPELINE_STAGE_ATTR` (appended under
  `fluid.device_guard("pp:<k>")`). Stage ids must be non-decreasing along
  the block's op order (the op list is already topological — a later op may
  not run on an earlier stage); unannotated ops inherit the surrounding
  stage.

- ANALYTIC: balance stages by per-op cost from the same counting model as
  `tools/mfu_audit.py` (dot FLOPs = 2·M·N·K, conv FLOPs = 2·out·Cin·kh·kw,
  everything else bandwidth-bound at in+out bytes), converted to microseconds
  against the measured v5e peaks so a matmul-heavy op and a byte-heavy op
  land on one scale, plus each op's parameter read bytes (a stage that owns
  more weight bytes pays more HBM traffic per microbatch). The cut minimizes
  the maximum stage weight over the LEGAL cut points the caller provides
  (a cut is legal when every live value crossing it is microbatch-major, so
  the schedule can pack it into the boundary buffer).
"""

import numpy as np

from ..framework import PIPELINE_STAGE_ATTR

__all__ = [
    "analytic_op_flops_bytes",
    "analytic_op_time_us",
    "stages_from_attrs",
    "balanced_partition",
]

# measured single-chip peaks from tools/mfu_audit.py (v5e bf16 matmul and
# large-fusion HBM bandwidth); only their RATIO matters here — the partition
# is invariant to rescaling both.
_PEAK_MM_FLOPS_PER_US = 192.0e6  # 192 TFLOP/s
_PEAK_BW_BYTES_PER_US = 676.0e3  # 676 GB/s


def _size(aval):
    return int(np.prod(aval.shape)) if aval.shape else 1


def _bytes(aval):
    return _size(aval) * np.dtype(aval.dtype).itemsize


def analytic_op_flops_bytes(op_type, in_avals, out_avals):
    """(flops, bytes) estimate for one op — the counting model underneath
    analytic_op_time_us, exposed separately so observability/opprof.py can
    report per-op FLOPs with the SAME numbers the pipeline partitioner
    balances on.

    in_avals: {slot: [aval, ...]} of the op's inputs; out_avals likewise.
    Mirrors HloIndex.instr_flops' counting (tools/mfu_audit.py) at the
    Program level: dot-family ops get 2·M·N·K, conv gets
    2·out_elems·Cin·kh·kw, everything else is bandwidth-bound.
    """
    flat_in = [a for vs in in_avals.values() for a in vs if a is not None]
    flat_out = [a for vs in out_avals.values() for a in vs if a is not None]
    nbytes = sum(_bytes(a) for a in flat_in) + sum(_bytes(a) for a in flat_out)
    flops = 0
    if op_type in ("mul", "matmul") and flat_out:
        out = flat_out[0]
        ys = in_avals.get("Y") or []
        if ys and out.shape:
            y = ys[0]
            # contraction length: mul flattens to [M,K]@[K,N]; matmul keeps
            # batch dims, contracting y's second-to-last (or only) dim
            k = y.shape[-2] if len(y.shape) >= 2 else (y.shape[0] if y.shape else 1)
            flops = 2 * _size(out) * int(k)
    elif op_type in ("conv2d", "depthwise_conv2d", "conv2d_transpose") and flat_out:
        out = flat_out[0]
        fs = in_avals.get("Filter") or []
        if fs:
            f = fs[0]
            # filter [Co, Ci, kh, kw] → per-output-elem 2·Ci·kh·kw MACs
            per_out = 2 * int(np.prod(f.shape[1:]))
            flops = _size(out) * per_out
    elif op_type in ("lstm", "gru", "sequence_conv") and flat_out:
        # recurrent mats dominate: approximate as bandwidth + 2·out·hidden
        out = flat_out[0]
        h = out.shape[-1] if out.shape else 1
        flops = 2 * _size(out) * int(h)
    return flops, nbytes


def analytic_op_time_us(op_type, in_avals, out_avals):
    """Roofline time estimate for one op: max(FLOP time, byte time), from
    analytic_op_flops_bytes against the measured v5e peaks."""
    flops, nbytes = analytic_op_flops_bytes(op_type, in_avals, out_avals)
    return max(flops / _PEAK_MM_FLOPS_PER_US, nbytes / _PEAK_BW_BYTES_PER_US)


def stages_from_attrs(ops, n_stages):
    """Explicit device_guard override: returns a per-op stage-id list, or
    None when no op carries the attr. Unannotated ops inherit the previous
    op's stage (stage 0 before the first annotation); annotations must be
    non-decreasing and < n_stages."""
    if not any(op.attrs.get(PIPELINE_STAGE_ATTR) is not None for op in ops):
        return None
    stages = []
    cur = 0
    for op in ops:
        s = op.attrs.get(PIPELINE_STAGE_ATTR)
        if s is not None:
            s = int(s)
            if s < cur:
                raise ValueError(
                    "device_guard stage %d on op %r goes BACKWARD from stage "
                    "%d: pipeline stages must be non-decreasing in program "
                    "order" % (s, op.type, cur)
                )
            if s >= n_stages:
                raise ValueError(
                    "device_guard stage %d on op %r >= pipeline depth %d"
                    % (s, op.type, n_stages)
                )
            cur = s
        stages.append(cur)
    return stages


def balanced_partition(weights, legal_cuts, n_stages):
    """Cut `weights` (per-op cost, program order) into `n_stages` contiguous
    segments minimizing the max segment weight, cutting only AFTER indices in
    `legal_cuts` (cut k = boundary between op k and op k+1). Returns the
    per-op stage-id list.

    Feasibility check + binary search over the bottleneck value with a
    greedy placement (cut at the last legal point that keeps the running
    segment under the bound) — exact for this minimax objective on a
    sequence with restricted cut points.
    """
    n = len(weights)
    if n_stages < 1:
        raise ValueError("n_stages must be >= 1")
    if n_stages == 1:
        return [0] * n
    legal = sorted(set(int(k) for k in legal_cuts if 0 <= int(k) < n - 1))
    if len(legal) < n_stages - 1:
        raise ValueError(
            "cannot cut %d ops into %d pipeline stages: only %d legal cut "
            "points (values crossing the others are not microbatch-major; "
            "pin stages explicitly with device_guard or lower pp)"
            % (n, n_stages, len(legal))
        )

    def greedy(bound):
        """Stage-id assignment with every segment <= bound, using at most
        n_stages segments and leaving enough legal cuts for the rest; None
        if infeasible."""
        cuts = []
        seg_start = 0
        i = 0
        li = 0  # index into legal
        acc = 0.0
        for i in range(n):
            acc += weights[i]
            remaining_stages = n_stages - 1 - len(cuts)
            if acc > bound and remaining_stages > 0:
                # cut at the last legal point in [seg_start, i-1]
                best = None
                for k in legal:
                    if seg_start <= k < i:
                        best = k
                if best is None:
                    return None
                cuts.append(best)
                seg_start = best + 1
                acc = float(sum(weights[seg_start : i + 1]))
                if acc > bound:
                    return None
        # force remaining cuts (every stage must be non-empty of ops? allow
        # trailing cuts at remaining legal points after seg_start)
        while len(cuts) < n_stages - 1:
            nxt = [k for k in legal if k >= seg_start and k < n - 1 and k not in cuts]
            if not nxt:
                return None
            cuts.append(nxt[0])
            seg_start = nxt[0] + 1
        return sorted(cuts)

    lo = max(weights) if weights else 0.0
    hi = float(sum(weights)) or 1.0
    best_cuts = greedy(hi)
    if best_cuts is None:
        # bound=total always feasible given enough legal cuts
        raise ValueError("internal: partition infeasible at total weight")
    for _ in range(48):
        mid = 0.5 * (lo + hi)
        got = greedy(mid)
        if got is None:
            lo = mid
        else:
            hi = mid
            best_cuts = got
    stages = []
    cur = 0
    cut_set = set(best_cuts)
    for i in range(n):
        stages.append(cur)
        if i in cut_set:
            cur += 1
    return stages
