"""Multi-host initialization over DCN.

Reference analog: the NCCL2 multi-node mode — gen_nccl_id_op.cc:31-110 has
rank 0 serve an ncclUniqueId over a temporary gRPC server, after which
NCCLContextMap forms a num_trainers x nGPU world (nccl_helper.h:104-120).
On TPU the same rendezvous is jax.distributed.initialize against the
coordination service; afterwards jax.devices() spans all hosts and the SPMD
mesh simply includes them (dp axis over DCN)."""

import os

import jax

__all__ = ["init_distributed", "host_count", "host_index"]

_initialized = False


def host_count():
    """Processes in the job: jax.process_count() after a rendezvous, else
    the PADDLE_TRAINER_ENDPOINTS list length (the elastic runtime needs the
    intended topology BEFORE initialize, e.g. to size checkpoint shards)."""
    try:
        n = jax.process_count()
        if n > 1:
            return n
    except RuntimeError:
        pass
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    return len(eps.split(",")) if eps else 1


def host_index():
    """This process's rank: jax.process_index() after a rendezvous, else
    PADDLE_TRAINER_ID."""
    try:
        i = jax.process_index()
        if i or host_count() == 1:
            return i
    except RuntimeError:
        pass
    return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)


def init_distributed(
    coordinator_address=None, num_processes=None, process_id=None
):
    """Call once per host before building meshes. Arguments default from the
    fluid-style env vars the reference's transpiler mode used
    (PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINER_ID, SURVEY.md §3.4) and fall
    back to JAX's own cluster autodetection."""
    global _initialized
    if _initialized:
        return
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        if eps:
            coordinator_address = eps.split(",")[0]
            num_processes = num_processes or len(eps.split(","))
    if process_id is None:
        process_id = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    if coordinator_address is None:
        # single host — nothing to rendezvous
        _initialized = True
        return
    # the rendezvous is the single most preemption-exposed moment of a
    # multi-host job (the coordinator pod may come up seconds after the
    # workers); retry under the unified policy instead of dying on the
    # first connection refusal (FLAGS_dist_init_max_retry)
    from .. import flags as _flags
    from ..resilience import health as _health
    from ..resilience.retry import RetryPolicy

    attempts = int(_flags.get_flags("dist_init_max_retry")["dist_init_max_retry"]) + 1
    # decorrelated jitter, seeded per-rank: when a whole pod restarts after
    # a preemption, every host fails attempt 1 at the same instant — the
    # lockstep exponential schedule would hammer the coordinator in waves,
    # decorrelated draws spread the herd (resilience/retry.py docstring)
    policy = RetryPolicy(
        max_attempts=attempts,
        base_delay=0.5,
        max_delay=5.0,
        jitter="decorrelated",
        seed=process_id,
        retryable=(RuntimeError, ConnectionError, OSError),
    )
    policy.call(
        jax.distributed.initialize,
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        on_retry=lambda _a, _e: _health.incr("dist_init_retries"),
    )
    _initialized = True
