"""TPU-native parallelism package.

The reference's parallelism machinery (SURVEY.md §2.2, §2.7) — ParallelExecutor
SSA graphs + NCCL, gRPC parameter servers, distributed lookup tables — maps
here onto jax.sharding over a device Mesh:

- data parallel (dp): batch-sharded feeds, replicated params (parallel_executor.py)
- fully-sharded data parallel (fsdp): params+grads+moments sharded over the
  fsdp axis with all-gather-on-use, declared via sharding_rules
- tensor parallel (tp): parameter PartitionSpecs via sharding_rules
  (declarative regex -> spec engine) or shard_parameter (per-var attr)
- sequence/context parallel (sp): ring attention over ICI (ring_attention.py)
- embedding parallel (ep): row-sharded tables with psum combine (sharded_embedding)
- multi-host: jax.distributed over DCN (multihost.py), replacing the
  reference's gen_nccl_id gRPC rendezvous (gen_nccl_id_op.cc:31-110)
"""

from .mesh import MeshConfig, make_mesh
from .multihost import init_distributed
from .pipeline import (
    gpipe,
    gpipe_spmd,
    pipeline_1f1b_spmd,
    pipeline_fwd_spmd,
)
from .ring_attention import ring_attention
from .sharding_rules import ShardingRules, SpecLayout, program_rules
from . import collectives
from . import partition
from . import sharding_rules

__all__ = [
    "gpipe",
    "gpipe_spmd",
    "pipeline_fwd_spmd",
    "pipeline_1f1b_spmd",
    "partition",
    "MeshConfig",
    "make_mesh",
    "init_distributed",
    "ring_attention",
    "collectives",
    "shard_parameter",
    "sharding_rules",
    "ShardingRules",
    "SpecLayout",
    "program_rules",
]


def shard_parameter(param, spec):
    """Annotate a Parameter with a PartitionSpec-like tuple (e.g. (None, 'tp'))
    consumed by the SPMD executor instead of the default replication — the
    TPU-native 'model parallelism' the reference only had for sparse tables
    (distributed lookup table, SURVEY.md §2.7.5)."""
    param.sharding_spec = tuple(spec)
    return param
