"""PyReader: decoupled async input pipeline with device-side prefetch.

Reference analog: layers/io.py:633 py_reader + operators/reader/
lod_tensor_blocking_queue.h + buffered_reader (double-buffer prefetch to
device). A feeder thread pulls numpy batches from the user's reader, stages
them on device (jax.device_put) AHEAD of compute, and the executor pops the
staged batch at each run — overlapping host->device transfer with the previous
step's compute, which is exactly what the reference's double_buffer reader did
with CUDA streams. EOF surfaces as EOFException caught by the train loop
(reference fluid_benchmark.py:244-246 pattern).
"""

import queue as Queue
import threading

import jax

__all__ = ["PyReader", "EOFException"]


class EOFException(Exception):
    """reference core.EOFException"""


class _EndOfEpoch:
    pass


class _FeederError:
    """Feeder-thread exception carrier: re-raised in the consumer so a
    crashing reader/assembly/device_put surfaces instead of reading as a
    clean EOF (which would silently truncate an epoch)."""

    def __init__(self, exc):
        self.exc = exc


class PyReader:
    def __init__(self, feed_names, capacity=4, return_device_arrays=True,
                 wire_dtypes=None, cache_epoch=False):
        """wire_dtypes: optional {feed_name: dtype} COMPACT WIRE FORMAT —
        batches are converted to this dtype on the host before staging, so
        the host->device transfer carries e.g. uint8 pixels (4x fewer bytes
        than f32) or bf16 activations (2x); the executor's trace-time
        declared-dtype cast (executor._CompiledBlock feed_want) then converts
        to the program's var dtype ON DEVICE, fused into the compiled step.
        Reference analog: the double-buffer reader moves whatever dtype the
        LoDTensor holds (operators/reader/buffered_reader.h:48) — uint8
        image feeds + an in-graph cast were the reference's own trick for
        byte-bound input pipelines.

        cache_epoch: DEVICE-RESIDENT EPOCH CACHE. The first epoch runs the
        normal path (reader → host assembly → wire → device staging) and
        additionally retains every staged batch; once the epoch completes
        cleanly, later start() calls replay the cached device arrays through
        the same queue/feeder machinery with the reader, host assembly, and
        the host->device wire all out of the loop. For an image set that
        fits HBM this removes the wire-bound stage entirely
        (PIPELINE_KEEPUP.json keep-up evidence; tools/pipeline_probe.py
        measures the replay rate). Caching keys off the staged arrays, so a
        new decorate_* call or a mid-epoch reset() invalidates it."""
        self.feed_names = list(feed_names)
        self.capacity = capacity
        self._cache_epoch = bool(cache_epoch)
        self._cache = None  # completed-epoch staged batches, serve order
        self._cache_building = None
        self._wire_dtypes = {
            k: (jax.numpy.bfloat16 if str(v) == "bfloat16" else v)
            for k, v in (wire_dtypes or {}).items()
        }
        self._queue = None
        self._thread = None
        self._stop = None
        self._paddle_reader = None
        self._feeder = None
        self._batched_tuples = False
        self._return_device = return_device_arrays
        self._started = False
        self._eof_deferred = False

    # --- decoration (reference py_reader.decorate_paddle_reader) ---
    def decorate_paddle_reader(self, reader, places=None):
        """reader yields batches as lists of sample tuples (paddle.batch
        output). Without an attached DataFeeder the columns are stacked
        dense; ragged (LoD) fields need a DataFeeder (set_feeder)."""
        self._paddle_reader = reader
        self._batched_tuples = True
        self._cache = None  # new dataset: cached epoch no longer valid
        return self

    def decorate_tensor_provider(self, reader):
        """reader yields dicts name->numpy directly"""
        self._paddle_reader = reader
        self._raw_dicts = True
        self._cache = None
        return self

    def decorate_batch_generator(self, reader, places=None):
        return self.decorate_tensor_provider(reader)

    def set_feeder(self, feeder):
        self._feeder = feeder
        return self

    @property
    def started(self):
        """Same contract as the program-registered reader handles
        (layers/io.py) so Executor.run can pull from either kind."""
        return self._started

    # --- lifecycle ---
    def start(self):
        if self._started:
            raise RuntimeError("PyReader already started; call reset() first")
        self._queue = Queue.Queue(maxsize=self.capacity)
        self._stop = threading.Event()
        self._started = True
        # a previous partial multi-step pull may have deferred its epoch-end
        # signal (executor._pull_reader_steps); a restart begins a new epoch
        self._eof_deferred = False

        # local refs: reset() swaps these out mid-epoch
        q = self._queue
        stop = self._stop

        def _convert(item):
            if isinstance(item, dict):
                return item
            if self._feeder is not None:
                return self._feeder.feed(item)
            if self._batched_tuples:
                # list of sample tuples (paddle.batch output) → column-stacked
                import numpy as np

                cols = list(zip(*item))
                return {
                    name: np.stack([np.asarray(v) for v in col])
                    for name, col in zip(self.feed_names, cols)
                }
            return dict(zip(self.feed_names, item))

        def _put(value):
            while not stop.is_set():
                try:
                    q.put(value, timeout=0.1)
                    return True
                except Queue.Full:
                    continue
            return False

        building = [] if (self._cache_epoch and self._cache is None) else None

        def fill():
            try:
                for item in self._paddle_reader():
                    if stop.is_set():
                        return
                    feed = _convert(item)
                    if self._wire_dtypes:
                        import numpy as np

                        feed = {
                            k: (
                                np.asarray(v).astype(self._wire_dtypes[k])
                                if k in self._wire_dtypes
                                else v
                            )
                            for k, v in feed.items()
                        }
                    if self._return_device:
                        # stage on device ahead of compute (double buffering)
                        feed = {k: jax.device_put(v) for k, v in feed.items()}
                    if building is not None:
                        building.append(feed)
                    if not _put(feed):
                        return
                # clean epoch end: the staged batches ARE the epoch — keep
                # them on device for wire-free replay next epoch
                if building is not None:
                    self._cache = building
            except BaseException as e:  # noqa: B036 — carried to the consumer
                _put(_FeederError(e))
                return
            finally:
                _put(_EndOfEpoch)

        def replay():
            # cached-epoch path: same queue/consumer machinery, but the
            # reader, host assembly, and host->device wire are not involved
            for feed in self._cache:
                if stop.is_set():
                    return
                if not _put(feed):
                    return
            _put(_EndOfEpoch)

        serve_cached = self._cache_epoch and self._cache is not None
        self._thread = threading.Thread(
            target=replay if serve_cached else fill, daemon=True
        )
        self._thread.start()

    def reset(self):
        """Stop the feeder thread (reference reader ResetAll); safe to call
        mid-epoch — the thread exits and its staged buffers are dropped."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5)
        self._started = False
        self._queue = None
        # pushed-back batches are staged state too: a batch returned by the
        # executor's mid-step-EOF pushback must not leak into the next
        # epoch (or a new decorated dataset)
        if getattr(self, "_pushed_back", None):
            self._pushed_back.clear()
        self._thread = None
        self._stop = None
        self._eof_deferred = False

    def next_batch(self):
        if not self._started:
            raise RuntimeError("PyReader not started")
        pushed = getattr(self, "_pushed_back", None)
        if pushed:
            return pushed.popleft()
        # telemetry: time blocked on the staging queue — that is the input
        # pipeline failing to keep up (the device would idle exactly this
        # long), recorded as feed-stall on the next step
        # (observability/stepstats.py; only when telemetry is active)
        from .observability import stepstats as _ss

        if _ss.active():
            import time as _time

            t0 = _time.perf_counter()
            item = self._queue.get()
            _ss.collector().add_feed_stall((_time.perf_counter() - t0) * 1e3)
        else:
            item = self._queue.get()
        if isinstance(item, _FeederError):
            self._started = False
            raise item.exc
        if item is _EndOfEpoch:
            self._started = False
            raise EOFException("reader exhausted")
        return item

    def push_back(self, batch):
        """Return a consumed batch to the FRONT of the queue. Used by the
        executor's multi-reader step assembly: when a sibling reader hits
        EOF mid-step, batches already pulled from the other readers for
        that incomplete step are pushed back rather than dropped."""
        import collections

        if not hasattr(self, "_pushed_back"):
            self._pushed_back = collections.deque()
        self._pushed_back.appendleft(batch)

    def __call__(self):  # iterate batches
        try:
            while True:
                yield self.next_batch()
        except EOFException:
            return
