"""PyReader: decoupled async input pipeline with device-side prefetch.

Reference analog: layers/io.py:633 py_reader + operators/reader/
lod_tensor_blocking_queue.h + buffered_reader (double-buffer prefetch to
device). A feeder thread pulls numpy batches from the user's reader, stages
them on device (jax.device_put) AHEAD of compute, and the executor pops the
staged batch at each run — overlapping host->device transfer with the previous
step's compute, which is exactly what the reference's double_buffer reader did
with CUDA streams. EOF surfaces as EOFException caught by the train loop
(reference fluid_benchmark.py:244-246 pattern).
"""

import queue as Queue
import threading

import jax

__all__ = ["PyReader", "EOFException"]


class EOFException(Exception):
    """reference core.EOFException"""


class _EndOfEpoch:
    pass


class _FeederError:
    """Feeder-thread exception carrier: re-raised in the consumer so a
    crashing reader/assembly/device_put surfaces instead of reading as a
    clean EOF (which would silently truncate an epoch)."""

    def __init__(self, exc):
        self.exc = exc


def _convert_item(item, feed_names, batched_tuples, feeder):
    """One reader item -> {name: ndarray} feed dict. Module-level so the
    data-runtime decode workers run the SAME assembly the feeder thread
    would (and so it pickles under spawn)."""
    if isinstance(item, dict):
        return item
    if feeder is not None:
        return feeder.feed(item)
    if batched_tuples:
        # list of sample tuples (paddle.batch output) → column-stacked
        import numpy as np

        cols = list(zip(*item))
        return {
            name: np.stack([np.asarray(v) for v in col])
            for name, col in zip(feed_names, cols)
        }
    return dict(zip(feed_names, item))


def _apply_wire(feed, wire_dtypes):
    if not wire_dtypes:
        return feed
    import numpy as np

    return {
        k: (np.asarray(v).astype(wire_dtypes[k]) if k in wire_dtypes else v)
        for k, v in feed.items()
    }


class _ShardedDecode:
    """decode_fn adapter handed to data.DataRuntime (num_workers mode).

    Two shapes of user reader:
    - shard factory ``reader(shard_id, num_shards)``: the reader opens only
      its slice of the dataset — true decode parallelism, the shape to use.
    - plain ``reader()`` (classic paddle reader): worker ``s`` iterates the
      full reader and keeps batches with ``index % num_shards == s``.
      Decode work is duplicated per worker, but batch assembly, wire-dtype
      conversion, shm packing, and device staging still parallelize, and
      the pipeline overlaps training — the reader must be deterministic
      (same batches in the same order every call), which the crash-replay
      contract requires anyway.

    Conversion (column stacking, DataFeeder, wire dtypes) runs HERE, in the
    worker process — the single-threaded feeder's biggest CPU costs move
    off the trainer. No jax imports on this path.
    """

    def __init__(self, reader, factory, num_shards, feed_names,
                 batched_tuples, feeder, wire_dtypes):
        self.reader = reader
        self.factory = bool(factory)
        self.num_shards = int(num_shards)
        self.feed_names = list(feed_names)
        self.batched_tuples = bool(batched_tuples)
        self.feeder = feeder
        self.wire_dtypes = dict(wire_dtypes or {})

    def __call__(self, shard_id):
        if self.factory:
            items = self.reader(shard_id, self.num_shards)
        else:
            items = (
                item for i, item in enumerate(self.reader())
                if i % self.num_shards == shard_id
            )
        for item in items:
            yield _apply_wire(
                _convert_item(
                    item, self.feed_names, self.batched_tuples, self.feeder
                ),
                self.wire_dtypes,
            )


def _reader_is_shard_factory(reader):
    """True when ``reader`` accepts two positional args (shard_id,
    num_shards) — the shard-aware factory shape."""
    import inspect

    try:
        sig = inspect.signature(reader)
    except (TypeError, ValueError):
        return False
    pos = [
        p for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(pos) >= 2


class PyReader:
    def __init__(self, feed_names, capacity=4, return_device_arrays=True,
                 wire_dtypes=None, cache_epoch=False):
        """wire_dtypes: optional {feed_name: dtype} COMPACT WIRE FORMAT —
        batches are converted to this dtype on the host before staging, so
        the host->device transfer carries e.g. uint8 pixels (4x fewer bytes
        than f32) or bf16 activations (2x); the executor's trace-time
        declared-dtype cast (executor._CompiledBlock feed_want) then converts
        to the program's var dtype ON DEVICE, fused into the compiled step.
        Reference analog: the double-buffer reader moves whatever dtype the
        LoDTensor holds (operators/reader/buffered_reader.h:48) — uint8
        image feeds + an in-graph cast were the reference's own trick for
        byte-bound input pipelines.

        cache_epoch: DEVICE-RESIDENT EPOCH CACHE. The first epoch runs the
        normal path (reader → host assembly → wire → device staging) and
        additionally retains every staged batch; once the epoch completes
        cleanly, later start() calls replay the cached device arrays through
        the same queue/feeder machinery with the reader, host assembly, and
        the host->device wire all out of the loop. For an image set that
        fits HBM this removes the wire-bound stage entirely
        (PIPELINE_KEEPUP.json keep-up evidence; tools/pipeline_probe.py
        measures the replay rate). Caching keys off the staged arrays, so a
        new decorate_* call or a mid-epoch reset() invalidates it."""
        self.feed_names = list(feed_names)
        self.capacity = capacity
        self._cache_epoch = bool(cache_epoch)
        self._cache = None  # completed-epoch staged batches, serve order
        self._cache_building = None
        self._wire_dtypes = {
            k: (jax.numpy.bfloat16 if str(v) == "bfloat16" else v)
            for k, v in (wire_dtypes or {}).items()
        }
        self._queue = None
        self._thread = None
        self._stop = None
        self._paddle_reader = None
        self._feeder = None
        self._batched_tuples = False
        self._return_device = return_device_arrays
        self._started = False
        self._eof_deferred = False
        # epoch-generation tag: bumped by start()/reset()/decorate_*; any
        # state write-back from a feeder thread or the data runtime must
        # carry the CURRENT gen or be discarded (a stale thread finishing
        # its last batch after a reset+redecorate must not install its
        # epoch cache over the new dataset's)
        self._gen = 0
        # native data runtime (docs/data.md): decorate_*(num_workers=N)
        self._num_workers = None
        self._num_shards = None
        self._runtime = None
        self._runtime_active = False
        self._runtime_building = None
        self._device_sharding = None

    # --- decoration (reference py_reader.decorate_paddle_reader) ---
    def _decorate(self, reader, batched_tuples, num_workers, num_shards):
        self._gen += 1
        self._paddle_reader = reader
        self._batched_tuples = batched_tuples
        self._cache = None  # new dataset: cached epoch no longer valid
        self._num_workers = num_workers
        self._num_shards = num_shards
        if self._runtime is not None:  # new dataset: new worker pool
            self._runtime.close()
            self._runtime = None
            self._runtime_active = False
        return self

    def decorate_paddle_reader(self, reader, places=None, num_workers=None,
                               num_shards=None):
        """reader yields batches as lists of sample tuples (paddle.batch
        output). Without an attached DataFeeder the columns are stacked
        dense; ragged (LoD) fields need a DataFeeder (set_feeder).

        num_workers > 0 (or FLAGS_data_num_workers) routes decode through
        the native data runtime (paddle_tpu/data/, docs/data.md): reader
        batches decode in worker PROCESSES, cross into the trainer through
        a shared-memory ring, and device-stage ahead of compute. Pass a
        shard-aware factory ``reader(shard_id, num_shards)`` for true
        decode parallelism (num_shards defaults to 4x workers); a plain
        ``reader()`` falls back to round-robin batch mode (must be
        deterministic)."""
        return self._decorate(reader, True, num_workers, num_shards)

    def decorate_tensor_provider(self, reader, num_workers=None,
                                 num_shards=None):
        """reader yields dicts name->numpy directly (num_workers: as in
        decorate_paddle_reader)"""
        return self._decorate(reader, False, num_workers, num_shards)

    def decorate_batch_generator(self, reader, places=None, num_workers=None,
                                 num_shards=None):
        return self.decorate_tensor_provider(
            reader, num_workers=num_workers, num_shards=num_shards
        )

    def set_device_sharding(self, sharding):
        """Device placement for staged batches in num_workers mode — the
        ParallelExecutor installs its data-parallel NamedSharding here so
        batches arrive already sharded across the mesh. A callable
        ``sharding(array) -> Sharding|None`` is evaluated per field."""
        self._device_sharding = sharding
        if self._runtime is not None:
            self._runtime.device_sharding = sharding
        return self

    def set_feeder(self, feeder):
        self._feeder = feeder
        return self

    @property
    def started(self):
        """Same contract as the program-registered reader handles
        (layers/io.py) so Executor.run can pull from either kind."""
        return self._started

    # --- lifecycle ---
    def _resolved_workers(self):
        if self._num_workers is not None:
            return int(self._num_workers)
        from .flags import get_flags

        return int(get_flags()["data_num_workers"])

    def _ensure_runtime(self, num_workers):
        if self._runtime is not None:
            return self._runtime
        from .data import DataRuntime

        factory = _reader_is_shard_factory(self._paddle_reader)
        if self._num_shards:
            num_shards = int(self._num_shards)
        else:
            # round-robin mode re-decodes the full reader per shard, so
            # exactly one shard per worker; a shard factory gets 4x for
            # work-stealing balance across uneven shards
            num_shards = 4 * num_workers if factory else num_workers
        decode = _ShardedDecode(
            self._paddle_reader, factory, num_shards, self.feed_names,
            self._batched_tuples, self._feeder, self._wire_dtypes,
        )
        self._runtime = DataRuntime(
            decode, num_shards=num_shards, num_workers=num_workers,
            stage_device=self._return_device,
            device_sharding=self._device_sharding,
            device_prefetch=max(2, int(self.capacity) // 2),
            name="pyreader",
        )
        return self._runtime

    def start(self):
        if self._started:
            raise RuntimeError("PyReader already started; call reset() first")
        if self._paddle_reader is None:
            raise RuntimeError("PyReader has no decorated reader")
        self._gen += 1
        self._started = True
        # a previous partial multi-step pull may have deferred its epoch-end
        # signal (executor._pull_reader_steps); a restart begins a new epoch
        self._eof_deferred = False

        serve_cached = self._cache_epoch and self._cache is not None
        num_workers = self._resolved_workers()
        if num_workers > 0 and not serve_cached:
            # native data runtime path: no feeder thread in this process
            rt = self._ensure_runtime(num_workers)
            if rt.started:
                rt.reset()
            rt.start()
            self._runtime_active = True
            self._runtime_building = (
                [] if (self._cache_epoch and self._cache is None) else None
            )
            return
        self._runtime_active = False

        self._queue = Queue.Queue(maxsize=self.capacity)
        self._stop = threading.Event()

        # local refs: reset() swaps these out mid-epoch
        q = self._queue
        stop = self._stop
        gen = self._gen

        def _put(value):
            while not stop.is_set():
                try:
                    q.put(value, timeout=0.1)
                    return True
                except Queue.Full:
                    continue
            return False

        building = [] if (self._cache_epoch and self._cache is None) else None

        def fill():
            try:
                for item in self._paddle_reader():
                    if stop.is_set():
                        return
                    feed = _apply_wire(
                        _convert_item(
                            item, self.feed_names, self._batched_tuples,
                            self._feeder,
                        ),
                        self._wire_dtypes,
                    )
                    if self._return_device:
                        # stage on device ahead of compute (double buffering)
                        feed = {k: jax.device_put(v) for k, v in feed.items()}
                    if building is not None:
                        building.append(feed)
                    if not _put(feed):
                        return
                # clean epoch end: the staged batches ARE the epoch — keep
                # them on device for wire-free replay next epoch. Gen guard:
                # a stale thread (reset()/decorate_* raced its final batch)
                # must not install its cache over the new dataset's.
                if building is not None and gen == self._gen and not stop.is_set():
                    self._cache = building
            except BaseException as e:  # noqa: B036 — carried to the consumer
                _put(_FeederError(e))
                return
            finally:
                _put(_EndOfEpoch)

        def replay():
            # cached-epoch path: same queue/consumer machinery, but the
            # reader, host assembly, and host->device wire are not involved
            for feed in self._cache:
                if stop.is_set():
                    return
                if not _put(feed):
                    return
            _put(_EndOfEpoch)

        self._thread = threading.Thread(
            target=replay if serve_cached else fill, daemon=True
        )
        self._thread.start()

    def reset(self):
        """Stop the feeder thread / abort the runtime epoch (reference
        reader ResetAll); safe to call mid-epoch — staged batches are
        dropped, and the generation bump disowns any feeder thread that
        outlives the join (its late cache install / queue puts are
        discarded by the gen guard instead of leaking into the next
        epoch)."""
        self._gen += 1
        if self._runtime is not None and self._runtime_active:
            self._runtime.reset()
        self._runtime_active = False
        self._runtime_building = None
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5)
        self._started = False
        self._queue = None
        # pushed-back batches are staged state too: a batch returned by the
        # executor's mid-step-EOF pushback must not leak into the next
        # epoch (or a new decorated dataset)
        if getattr(self, "_pushed_back", None):
            self._pushed_back.clear()
        self._thread = None
        self._stop = None
        self._eof_deferred = False

    def _runtime_next(self):
        """num_workers mode: pull from the data runtime (which records its
        own feed-stall — no double counting with the thread path below)."""
        try:
            feed = self._runtime.next_batch()
        except EOFException:
            self._started = False
            if (
                self._runtime_building is not None
                and self._cache_epoch
                and self._cache is None
            ):
                self._cache = self._runtime_building
            self._runtime_building = None
            self._runtime_active = False
            raise
        if self._runtime_building is not None:
            self._runtime_building.append(feed)
        return feed

    def next_batch(self):
        if not self._started:
            raise RuntimeError("PyReader not started")
        pushed = getattr(self, "_pushed_back", None)
        if pushed:
            return pushed.popleft()
        if self._runtime_active:
            return self._runtime_next()
        # telemetry: time blocked on the staging queue — that is the input
        # pipeline failing to keep up (the device would idle exactly this
        # long), recorded as feed-stall on the next step
        # (observability/stepstats.py; only when telemetry is active)
        from .observability import stepstats as _ss

        if _ss.active():
            import time as _time

            t0 = _time.perf_counter()
            item = self._queue.get()
            _ss.collector().add_feed_stall((_time.perf_counter() - t0) * 1e3)
        else:
            item = self._queue.get()
        if isinstance(item, _FeederError):
            self._started = False
            raise item.exc
        if item is _EndOfEpoch:
            self._started = False
            raise EOFException("reader exhausted")
        return item

    def push_back(self, batch):
        """Return a consumed batch to the FRONT of the queue. Used by the
        executor's multi-reader step assembly: when a sibling reader hits
        EOF mid-step, batches already pulled from the other readers for
        that incomplete step are pushed back rather than dropped."""
        import collections

        if not hasattr(self, "_pushed_back"):
            self._pushed_back = collections.deque()
        self._pushed_back.appendleft(batch)

    def drain(self):
        """Preemption half-close (resilience/elastic.py Supervisor): stop
        producers and discard every staged/in-flight batch, counting what
        was dropped so the exit is observable. The reader stays decorated —
        a resumed incarnation re-decorates and starts fresh; exactly-once
        delivery is the data CURSOR's job (epoch + batch index in the
        checkpoint manifest), not the queue's."""
        dropped = 0
        pushed = getattr(self, "_pushed_back", None)
        if pushed:
            dropped += len(pushed)
        q = self._queue
        if q is not None:
            try:
                dropped += q.qsize()
            except (NotImplementedError, OSError):
                pass
        self.reset()
        if dropped:
            from .resilience import health as _health

            _health.incr("drain_batches_dropped", dropped)
        return dropped

    def close(self):
        """Release the worker pool / shared-memory ring of num_workers
        mode (idempotent; the thread path has nothing to release)."""
        self.reset()
        if self._runtime is not None:
            self._runtime.close()
            self._runtime = None

    def __call__(self):  # iterate batches
        try:
            while True:
                yield self.next_batch()
        except EOFException:
            return
