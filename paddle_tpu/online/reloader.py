"""HotReloader — applies published versions to live serving engines.

The reader half of the publish/reload protocol (docs/online.md): a daemon
poll loop (or an explicit ``check_once()`` — the testable face) watches the
model repository's LATEST.json pointer and, when it advances, lands the new
version in every registered engine WITHOUT recompiling or dropping requests:

- **incremental**: when the pointer stays on the engines' current base and
  the delta chain links cleanly past the applied version, each pending delta
  is replayed directly — dense params swap wholesale, touched table rows
  scatter into a COPY of the live table (copy-on-publish; the in-flight
  request keeps the old buffer) — one ``engine.set_params`` per version, so
  the ``model_version`` served with each response is a real published
  version, never a half-applied blend;
- **full**: a base change (compaction), a chain gap, or a cold start falls
  back to ``load_with_deltas`` — the same arrays an offline Predictor would
  restore, which is exactly what the bench's bit-parity assert checks;
- after catching up the reloader ACKs the version into the repository
  (online.staleness.write_ack) — the trainer's throttle input — and updates
  the ``online/serving_version`` + ``online/serving_staleness_steps`` /
  ``_seconds`` gauges (scraped via the ModelServer's /metrics).

Engines are anything with ``scope.vars``, ``set_params(updates, version=,
stamp=)`` and a ``name`` — ServingEngine and GenerationEngine both qualify.
A torn read (the publisher GC'ing underfoot) is counted, logged, and retried
at the next poll; the engines keep serving the version they have.
"""

import threading
import time
import warnings

import numpy as np

from ..observability import tracing as _tracing
from ..resilience import async_ckpt
from . import publisher as _publisher
from . import staleness as _staleness

__all__ = ["HotReloader"]


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


class HotReloader:
    """Keep live engines at the model repository's newest version."""

    def __init__(self, repo, engines, consumer="server", poll_interval_s=0.5,
                 contract=None):
        self.repo = repo
        if isinstance(engines, dict):
            self.engines = dict(engines)
        else:
            engines = list(engines)
            self.engines = {e.name: e for e in engines}
        if not self.engines:
            raise ValueError("HotReloader needs at least one engine")
        self.consumer = str(consumer)
        self.poll_interval = float(poll_interval_s)
        self.contract = contract or _staleness.StalenessContract()
        self.applied_version = None
        self.applied_base = None
        self.applied_stamp = {}
        self.reloads = 0
        self.errors = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

        reg = _registry()
        self._m_reloads = reg.counter(
            "online/reloads", "published versions applied to live engines"
        )
        self._m_errors = reg.counter(
            "online/reload_errors", "reload attempts that failed (retried)"
        )
        self._m_version = reg.gauge(
            "online/serving_version", "version live in the engines, by model"
        )
        self._m_lag_steps = reg.gauge(
            "online/serving_staleness_steps",
            "training steps the served version trails the newest published",
        )
        self._m_lag_secs = reg.gauge(
            "online/serving_staleness_seconds",
            "publisher-stamp seconds the served version trails the newest",
        )
        reg.gauge(
            "online/max_staleness_seconds",
            "the staleness contract's serving budget",
        ).set(self.contract.max_staleness_seconds)

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Begin the daemon poll loop (idempotent)."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hot-reloader", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout=10.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    def _loop(self):
        while not self._stop.wait(self.poll_interval):
            try:
                self.check_once()
            except Exception:
                # the poll loop must survive anything; check_once already
                # counted what it could
                self.errors += 1

    # -------------------------------------------------------------- polling
    def check_once(self):
        """One poll: read the pointer, apply anything new, ack, update the
        staleness gauges. Returns the number of versions applied (0 when
        already current or the repository is unreadable)."""
        with self._lock:
            return self._check_locked()

    def _check_locked(self):
        pointer = _publisher.read_latest(self.repo)
        if pointer is None:
            return 0
        latest = int(pointer["version"])
        stamp = dict(pointer.get("stamp") or {})
        if self.applied_version is not None and latest <= self.applied_version:
            self._set_gauges(stamp)
            return 0
        try:
            applied = self._apply_upto(pointer)
        except (IOError, OSError, KeyError, ValueError) as e:
            # publisher GC / a torn read underfoot: keep serving, retry
            self.errors += 1
            self._m_errors.inc()
            warnings.warn("hot reload of version %d failed (%r); retrying"
                          % (latest, e))
            return 0
        if applied:
            self.reloads += applied
            _staleness.write_ack(
                self.repo, self.consumer, self.applied_version,
                self.applied_stamp,
            )
            from ..observability import stepstats as _stepstats

            _stepstats.maybe_flush()
        self._set_gauges(stamp)
        return applied

    def _apply_upto(self, pointer):
        latest = int(pointer["version"])
        base_step = pointer.get("base_step")
        chain = async_ckpt.resolve_delta_chain(self.repo, upto_step=latest)
        if chain is None:
            raise IOError("no recoverable base in %s" % self.repo)
        rbase, _rdir, links = chain
        incremental = (
            self.applied_version is not None
            and self.applied_base == rbase
            and base_step == rbase
            and (self.applied_version == rbase
                 or any(s == self.applied_version for s, _ in links))
        )
        if incremental:
            pending = [(s, d) for s, d in links if s > self.applied_version]
            applied = 0
            for step, delta_dir in pending:
                self._apply_delta_live(step, delta_dir)
                applied += 1
            return applied
        # cold start / base changed / gap: full restore, one swap per engine
        loaded = async_ckpt.load_with_deltas(self.repo, upto_step=latest)
        if loaded is None:
            raise IOError("no loadable version in %s" % self.repo)
        step, arrays, info = loaded
        st = dict(info.get("stamp") or pointer.get("stamp") or {})
        for name, engine in self.engines.items():
            # force-kept root span: hot swaps are rare, operator-relevant
            # events — every one lands in the trace shards regardless of
            # the sampling rate, so a latency blip can be lined up with
            # the param swap that caused it
            with _tracing.tracer().start_span(
                "reloader.swap", kind="base", engine=name, version=step,
                arrays=len(arrays),
            ).force_keep():
                engine.set_params(arrays, version=step, stamp=st)
        self.applied_version = int(step)
        self.applied_base = info["base_step"]
        self.applied_stamp = st
        self._m_reloads.inc()
        return 1

    def _apply_delta_live(self, step, delta_dir):
        """Replay one delta onto each engine's live buffers: seed apply_delta
        with the engine's CURRENT table arrays (from its scope) so row
        scatters land on what is actually being served — copy-on-publish
        happens inside apply_delta."""
        manifest = async_ckpt._read_manifest(delta_dir)
        table_names = [
            n for n, m in manifest["arrays"].items() if m["kind"] == "rows"
        ]
        for name, engine in self.engines.items():
            seed = {}
            for n in table_names:
                cur = engine.scope.vars.get(n)
                if cur is not None:
                    seed[n] = np.asarray(cur)
            _s, updated, mf = async_ckpt.apply_delta(delta_dir, seed)
            updates = {
                n: updated[n] for n in mf["arrays"] if n in updated
            }
            st = dict(mf.get("stamp") or {})
            with _tracing.tracer().start_span(
                "reloader.swap", kind="delta", engine=name, version=step,
                arrays=len(updates),
            ).force_keep():
                engine.set_params(updates, version=step, stamp=st)
            self.applied_stamp = st
        self.applied_version = int(step)
        self.applied_base = manifest["base_step"]
        self._m_reloads.inc()

    # -------------------------------------------------------------- gauges
    def _set_gauges(self, latest_stamp):
        served = dict(self.applied_stamp or {})
        lag_steps = max(
            0,
            int(latest_stamp.get("train_step", 0))
            - int(served.get("train_step", 0)),
        ) if served else 0
        lag_secs = max(
            0.0,
            float(latest_stamp.get("wall_time", 0.0))
            - float(served.get("wall_time", 0.0)),
        ) if served else 0.0
        for name, engine in self.engines.items():
            self._m_version.set(
                float(getattr(engine, "model_version", 0) or 0), model=name
            )
            self._m_lag_steps.set(float(lag_steps), model=name)
            self._m_lag_secs.set(lag_secs, model=name)

    def stats(self):
        return {
            "applied_version": self.applied_version,
            "applied_base": self.applied_base,
            "reloads": self.reloads,
            "errors": self.errors,
            "consumer": self.consumer,
        }
