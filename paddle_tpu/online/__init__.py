"""paddle_tpu.online — the online learning loop (docs/online.md).

Streaming training that continuously feeds the serving fleet:

- OnlineTrainer (trainer.py): the PR 9 Supervisor over an unbounded batch
  stream, publishing the serve set every publish_interval steps;
- ModelPublisher (publisher.py): atomic base/delta versions + LATEST.json
  pointer into a model-repository directory;
- HotReloader (reloader.py): applies new versions to live
  ServingEngine/GenerationEngine param buffers — no recompile, no dropped
  requests;
- StalenessContract (staleness.py): publisher stamps, consumer acks, and the
  publish throttle bounding how far the fleet may trail the stream.
"""

from .publisher import LATEST, ModelPublisher, read_latest
from .reloader import HotReloader
from .staleness import (
    StalenessContract,
    behind_steps,
    read_acks,
    stamp,
    write_ack,
)
from .trainer import OnlineTrainer

__all__ = [
    "OnlineTrainer",
    "ModelPublisher",
    "HotReloader",
    "StalenessContract",
    "read_latest",
    "LATEST",
    "stamp",
    "write_ack",
    "read_acks",
    "behind_steps",
]
