"""The staleness contract between an online trainer and its serving fleet.

Reference analog: Downpour-style async training bounds how far a worker's
view may trail the parameter server; in the inverted (train→serve) direction
the bound is on the SERVER — how far the fleet may fall behind the stream.
The contract has three legs (docs/online.md):

- the publisher stamps every published version with the training step and
  wall time it was cut at (``stamp()``; the stamp rides the repository's
  LATEST.json pointer and each delta manifest);
- every consumer acknowledges the version it is actually serving by writing
  an atomic ``ack-<consumer>.json`` into the model repository
  (``write_ack``), and exposes ``online/serving_staleness_steps`` /
  ``online/serving_staleness_seconds`` gauges (set by the HotReloader);
- the trainer consults ``behind_steps`` before publishing and THROTTLES —
  skips the publish — once the slowest consumer trails by more than
  ``max_staleness_steps`` (StalenessContract.should_publish). Backpressure,
  not buffering: an unbounded publish backlog would only grow the delta
  chain a wedged server must eventually replay.

Everything here is pure bookkeeping over small JSON files; the atomic-write
ladder is borrowed from resilience.async_ckpt so a torn ack can never be
read back.
"""

import json
import os
import time

from ..resilience.async_ckpt import _atomic_write

__all__ = [
    "StalenessContract",
    "stamp",
    "write_ack",
    "read_acks",
    "behind_steps",
]

_ACK_PREFIX = "ack-"


def stamp(train_step, wall_time=None):
    """The publisher's version stamp: which training step cut this version,
    and when."""
    return {
        "train_step": int(train_step),
        "wall_time": float(time.time() if wall_time is None else wall_time),
    }


def write_ack(repo, consumer, version, stamp_dict):
    """Atomically record that `consumer` is now serving `version` (the
    version's publisher stamp rides along, so the trainer can compute
    step/second lag without reading any checkpoint)."""
    doc = {
        "consumer": str(consumer),
        "version": int(version),
        "train_step": int((stamp_dict or {}).get("train_step", version)),
        "stamp_wall_time": float((stamp_dict or {}).get("wall_time", 0.0)),
        "ack_wall_time": time.time(),
    }
    _atomic_write(
        os.path.join(repo, "%s%s.json" % (_ACK_PREFIX, consumer)),
        json.dumps(doc),
    )
    return doc


def read_acks(repo):
    """{consumer: ack dict} for every readable ack file; torn/unparseable
    acks are skipped (the writer is atomic, but a foreign file must not wedge
    the trainer)."""
    out = {}
    try:
        names = os.listdir(repo)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_ACK_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(repo, name)) as f:
                doc = json.load(f)
            out[doc.get("consumer", name[len(_ACK_PREFIX):-5])] = doc
        except (OSError, ValueError):
            continue
    return out


def behind_steps(repo, latest_train_step):
    """How many training steps the SLOWEST acknowledged consumer trails the
    given (about-to-be or just-published) version. No acks yet -> 0: a fleet
    that has not come up must not block the first publishes."""
    acks = read_acks(repo)
    if not acks:
        return 0
    slowest = min(int(a.get("train_step", 0)) for a in acks.values())
    return max(0, int(latest_train_step) - slowest)


class StalenessContract:
    """The trainer-side policy knobs, as one value object.

    max_staleness_steps bounds consumer lag in TRAINING steps (the publish
    throttle's trigger); max_staleness_seconds is the serving-side alerting
    bound the gauges are judged against (the reloader exports it as
    ``online/max_staleness_seconds`` so dashboards render the budget next to
    the measurement).
    """

    def __init__(self, max_staleness_steps=200, max_staleness_seconds=300.0):
        self.max_staleness_steps = int(max_staleness_steps)
        self.max_staleness_seconds = float(max_staleness_seconds)

    def should_publish(self, repo, train_step):
        """False iff publishing now would leave the slowest consumer more
        than max_staleness_steps behind `train_step` — the trainer then
        skips (throttles) and retries at the next interval."""
        return behind_steps(repo, train_step) <= self.max_staleness_steps

    def as_dict(self):
        return {
            "max_staleness_steps": self.max_staleness_steps,
            "max_staleness_seconds": self.max_staleness_seconds,
        }
