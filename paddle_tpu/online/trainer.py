"""OnlineTrainer — streaming training that feeds the serving fleet.

Composition, not a new runtime: the PR 9 resilience.Supervisor still owns
every step (watchdog, NaN escalation, preemption, periodic FULL-state
checkpoints for trainer resume), and this class adds the publishing loop on
top — every ``publish_interval`` successful steps it snapshots the SERVE
set (the inference-visible params, typically save_inference_model's
persistables) out of the scope and hands it to a ModelPublisher, which cuts
a base or a delta into the model repository.

Two checkpoint streams, two directories, on purpose (docs/online.md):

- ``<repo>``             — serve-only versions (base + deltas + LATEST.json),
                           consumed by HotReloaders; never used for resume;
- ``<repo>/trainer``     — the Supervisor's full-state eckpts (params AND
                           optimizer moments AND data cursor), used only by
                           ``resume()``. Publishing a serve-only base into
                           the same root would become the "newest
                           recoverable checkpoint" and silently drop the
                           optimizer state on the next resume.

Embedding deltas ride the SelectedRows gradient rows: each step fetches
every engine's ``<table>@GRAD@ROWS`` var and feeds it to
``EmbeddingEngine.note_touched``; at publish time ``touched_rows_since``
yields exactly the rows written since the last publish. Dense params ship
only when bytes changed (the publisher's snapshot diff).
"""

import os

import numpy as np

from ..resilience import elastic as _elastic
from . import publisher as _publisher
from . import staleness as _staleness

__all__ = ["OnlineTrainer"]


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


class OnlineTrainer:
    """Supervised streaming trainer publishing into a model repository."""

    def __init__(self, exe, program, repo, serve_names, publisher=None,
                 publish_interval=20, embeddings=None, scope=None,
                 trainer_root=None, ckpt_every=0, contract=None,
                 num_hosts=1, host_id=0):
        from ..embedding import engines_of

        self.exe = exe
        self.program = program
        self.repo = repo
        self.serve_names = list(serve_names)
        self.publish_interval = int(publish_interval)
        self.scope = scope
        self.embeddings = (
            list(embeddings) if embeddings is not None
            else engines_of(program)
        )
        self.publisher = publisher or _publisher.ModelPublisher(
            repo, num_hosts=num_hosts, host_id=host_id,
            contract=contract or _staleness.StalenessContract(),
        )
        self.sup = _elastic.Supervisor(
            exe, trainer_root or os.path.join(repo, "trainer"),
            program=program, scope=scope,
            num_hosts=num_hosts, host_id=host_id, ckpt_every=int(ckpt_every),
        )
        # rows vars exist only for engines whose grad actually flows as
        # SelectedRows in this program; fetch what's there, skip the rest
        block = program.global_block()
        self._rows_fetch = [
            e.touched_rows_var_name()
            for e in self.embeddings
            if e.touched_rows_var_name() in block.vars
        ]
        self._last_pub_step = 0
        self.steps = 0
        reg = _registry()
        self._m_steps = reg.counter(
            "online/train_steps", "stream batches trained"
        )
        self._m_rows = reg.counter(
            "online/rows_trained", "samples consumed off the stream"
        )

    # -------------------------------------------------------------- resume
    def resume(self, startup_program):
        """Run startup then overlay the newest full-state trainer
        checkpoint; primes step + data cursor. Returns (step, cursor)."""
        return self.sup.resume_or_init(startup_program)

    # ----------------------------------------------------------------- run
    def run(self, stream, fetch_list=None, max_steps=None):
        """Consume `stream` (an iterator of feed dicts — see
        async_executor.stream_batches — or any generator) until it drains or
        `max_steps` land. Publishes every `publish_interval` successful
        steps, subject to the staleness throttle. Returns the list of first-
        fetch means per publish interval (the online loss curve)."""
        fetch_list = list(fetch_list or [])
        curve = []
        window = []
        with self.sup:
            for feed in stream:
                fetches = self.sup.run_step(
                    program=self.program, feed=feed,
                    fetch_list=fetch_list + self._rows_fetch,
                    scope=self.scope,
                )
                user = fetches[: len(fetch_list)]
                rows_vals = fetches[len(fetch_list):]
                self._note_touched(rows_vals)
                self.steps += 1
                self._m_steps.inc()
                if feed:
                    first = next(iter(feed.values()))
                    self._m_rows.inc(int(np.asarray(first).shape[0]))
                if user:
                    window.append(float(np.asarray(user[0]).reshape(-1)[0]))
                if self.publish_interval and \
                        self.sup.step % self.publish_interval == 0:
                    self.maybe_publish()
                    if window:
                        curve.append(float(np.mean(window)))
                        window.clear()
                if max_steps is not None and self.steps >= int(max_steps):
                    break
        if window:
            curve.append(float(np.mean(window)))
        return curve

    def _note_touched(self, rows_vals):
        by_name = dict(zip(self._rows_fetch, rows_vals))
        for e in self.embeddings:
            val = by_name.get(e.touched_rows_var_name())
            if val is not None:
                e.note_touched(self.sup.step, np.asarray(val))

    # ------------------------------------------------------------- publish
    def maybe_publish(self, force_base=False):
        """Publish the serve set now unless the staleness throttle says the
        fleet is too far behind. Returns the committed pointer or None."""
        if not force_base and not self.publisher.should_publish(self.sup.step):
            from ..observability import flightrec as _flightrec

            _flightrec.trigger("staleness_throttle", step=self.sup.step)
            return None
        return self.publish(force_base=force_base)

    def publish(self, force_base=False):
        """Unconditionally cut a version from the live scope."""
        from ..executor import global_scope

        scope = self.scope or global_scope()
        arrays = {}
        for name in self.serve_names:
            val = scope.find_var(name)
            if val is None:
                raise KeyError("serve var %r absent from scope" % name)
            arrays[name] = val
        touched = {
            e.table.name: e.touched_rows_since(self._last_pub_step)
            for e in self.embeddings
            if e.table.name in arrays
        }
        rec = self.publisher.publish(
            arrays, self.sup.step, touched=touched,
            cursor=dict(self.sup.cursor), force_base=force_base,
        )
        if rec is not None:
            self._last_pub_step = self.sup.step
        return rec

    def stats(self):
        out = {
            "steps": self.steps,
            "sup_step": self.sup.step,
            "last_publish_step": self._last_pub_step,
        }
        out.update(self.publisher.stats())
        return out
