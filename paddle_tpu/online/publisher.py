"""ModelPublisher — atomic version publishing into a model repository.

The repository is a plain directory speaking the resilience.async_ckpt
format: full base checkpoints (``eckpt-%08d``) plus incremental delta chains
(``eckpt-delta-%08d``), topped by an atomic ``LATEST.json`` pointer naming
the newest committed version. Readers (online.reloader.HotReloader, an
offline inference.Predictor rebuild) never coordinate with the writer: a
version exists iff its manifest landed, and the pointer — written with the
same tmp→fsync→rename ladder, strictly AFTER the manifest — only ever names
committed versions.

Publish policy (docs/online.md):

- the FIRST publish, any ``force_base``, and every time the live chain
  reaches ``max_chain`` links cuts a full base (compaction) — bounding both
  replay length for a cold reader and the window a lost delta can cost;
- otherwise a delta ships only what changed since the previous publish:
  dense params that fail a bytes-equal check against the last published
  snapshot, and embedding tables as (touched row ids, row values) from the
  EmbeddingEngine's SelectedRows bookkeeping;
- after a base commits, the stale chain (deltas rooted at older bases) is
  GC'd manifest-first; base GC itself is write_elastic_checkpoint's
  ``keep_last``;
- a publish is SKIPPED (returns None) when nothing changed, and THROTTLED
  when the slowest acknowledged consumer trails the last published version
  by more than the staleness contract's budget — see online.staleness.
"""

import json
import os
import time

import numpy as np

from ..resilience import async_ckpt
from ..resilience.async_ckpt import _atomic_write
from . import staleness as _staleness

__all__ = ["ModelPublisher", "LATEST", "read_latest"]

LATEST = "LATEST.json"


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


def read_latest(repo):
    """The repository's LATEST.json pointer dict, or None when absent or
    torn (the writer is atomic; tolerance here is for foreign files)."""
    try:
        with open(os.path.join(repo, LATEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class ModelPublisher:
    """One trainer's publishing face onto a model-repository directory."""

    def __init__(self, repo, num_hosts=1, host_id=0, keep_bases=2,
                 max_chain=8, contract=None, name=None):
        self.repo = repo
        self.num_hosts = int(num_hosts)
        self.host_id = int(host_id)
        self.keep_bases = int(keep_bases)
        self.max_chain = int(max_chain)
        self.contract = contract or _staleness.StalenessContract()
        self.name = name or os.path.basename(os.path.normpath(repo)) or "repo"
        os.makedirs(repo, exist_ok=True)

        # adopt an existing chain (trainer restart onto a live repository)
        self._base_step = None
        self._parent_step = None
        self._chain_len = 0
        self._last_train_step = None
        found = async_ckpt.resolve_delta_chain(repo)
        if found is not None:
            base_step, _d, chain = found
            self._base_step = base_step
            self._parent_step = chain[-1][0] if chain else base_step
            self._chain_len = len(chain)
            self._last_train_step = self._parent_step
        # dense snapshots from the last publish, for dirtiness checks; the
        # adopted case starts empty, so the first delta after a restart
        # conservatively ships every dense param
        self._last_dense = {}
        self.published = 0
        self.throttled = 0
        self.skipped_clean = 0

        reg = _registry()
        self._m_publishes = reg.counter(
            "online/publishes", "versions published, by kind label"
        )
        self._m_throttled = reg.counter(
            "online/publish_throttled",
            "publishes skipped because a consumer exceeded max_staleness",
        )
        self._m_skipped = reg.counter(
            "online/publish_skipped_clean",
            "publish intervals with nothing dirty to ship",
        )
        self._m_version = reg.gauge(
            "online/published_version", "newest committed version (train step)"
        )
        self._m_chain = reg.gauge(
            "online/delta_chain_len", "deltas since the live base"
        )
        self._m_ms = reg.histogram(
            "online/publish_ms", "wall ms per committed publish"
        )
        self._m_bytes = reg.counter(
            "online/published_bytes", "payload bytes shipped, by kind label"
        )

    # ---------------------------------------------------------------- policy
    def should_publish(self, train_step=None):
        """The staleness throttle: False while the slowest acknowledged
        consumer trails the last PUBLISHED version by more than the
        contract's step budget (a wedged fleet can only catch up to what is
        already published — pushing more would just grow its replay debt).
        `train_step` is unused in the decision but kept for callers logging
        intent."""
        if self._last_train_step is None:
            return True  # nothing published yet: nothing to be behind on
        ok = self.contract.should_publish(self.repo, self._last_train_step)
        if not ok:
            self.throttled += 1
            self._m_throttled.inc()
        return ok

    # --------------------------------------------------------------- publish
    def publish(self, arrays, train_step, touched=None, cursor=None,
                force_base=False):
        """Commit one version of `arrays` (name -> full array, the serve
        set) stamped with `train_step`. `touched` maps embedding table name
        -> row ids updated since the LAST publish; tables named there ship
        rows-only in delta mode. Returns the pointer dict committed, or None
        when a delta publish found nothing dirty."""
        t0 = time.perf_counter()
        touched = {
            n: np.asarray(ids).reshape(-1)
            for n, ids in (touched or {}).items()
            if n in arrays
        }
        want_base = (
            force_base
            or self._base_step is None
            or self._chain_len >= self.max_chain
        )
        st = _staleness.stamp(train_step)
        if want_base:
            pointer = self._publish_base(arrays, train_step, cursor, st)
        else:
            pointer = self._publish_delta(
                arrays, train_step, touched, cursor, st
            )
            if pointer is None:
                self.skipped_clean += 1
                self._m_skipped.inc()
                return None
        self._snapshot_dense(arrays, touched)
        self._last_train_step = int(train_step)
        self.published += 1
        self._m_version.set(float(train_step))
        self._m_chain.set(float(self._chain_len))
        self._m_ms.observe((time.perf_counter() - t0) * 1e3)
        return pointer

    def _publish_base(self, arrays, train_step, cursor, st):
        async_ckpt.write_elastic_checkpoint(
            self.repo, arrays, int(train_step),
            num_hosts=self.num_hosts, host_id=self.host_id,
            cursor=cursor, keep_last=self.keep_bases,
        )
        self._base_step = int(train_step)
        self._parent_step = int(train_step)
        self._chain_len = 0
        if self.host_id == 0:
            # the stale chains now root at GC'd/old bases: retire them
            # manifest-first so a reader mid-walk sees a skippable dir,
            # never a half-deleted manifest-ful one
            async_ckpt.gc_elastic_deltas(
                self.repo, keep_base_step=self._base_step
            )
        self._m_publishes.inc(kind="base")
        self._m_bytes.inc(
            int(sum(np.asarray(a).nbytes for a in arrays.values())),
            kind="base",
        )
        return self._write_pointer(train_step, "base", st)

    def _publish_delta(self, arrays, train_step, touched, cursor, st):
        dense = {}
        rows = {}
        nbytes = 0
        for name, a in arrays.items():
            if name in touched:
                ids = touched[name]
                if ids.size == 0:
                    continue
                full = np.asarray(a)
                vals = full[ids]
                rows[name] = (ids, vals, list(full.shape))
                nbytes += vals.nbytes + ids.nbytes
                continue
            cur = np.asarray(a)
            prev = self._last_dense.get(name)
            if prev is not None and prev.shape == cur.shape and \
                    np.array_equal(prev, cur):
                continue
            dense[name] = cur
            nbytes += cur.nbytes
        if not dense and not rows:
            return None
        async_ckpt.write_elastic_delta(
            self.repo, int(train_step), self._base_step, self._parent_step,
            dense, rows,
            num_hosts=self.num_hosts, host_id=self.host_id,
            cursor=cursor, stamp=st,
        )
        self._parent_step = int(train_step)
        self._chain_len += 1
        self._m_publishes.inc(kind="delta")
        self._m_bytes.inc(int(nbytes), kind="delta")
        return self._write_pointer(train_step, "delta", st)

    def _write_pointer(self, train_step, kind, st):
        pointer = {
            "version": int(train_step),
            "kind": kind,
            "base_step": self._base_step,
            "chain_len": self._chain_len,
            "stamp": st,
        }
        if self.host_id == 0:
            _atomic_write(
                os.path.join(self.repo, LATEST), json.dumps(pointer)
            )
        return pointer

    def _snapshot_dense(self, arrays, touched):
        # host copies of the dense set, the next delta's dirtiness baseline
        # (tables are excluded: their dirtiness is the touched-rows set)
        self._last_dense = {
            n: np.array(np.asarray(a))
            for n, a in arrays.items()
            if n not in touched
        }

    def stats(self):
        return {
            "published": self.published,
            "throttled": self.throttled,
            "skipped_clean": self.skipped_clean,
            "base_step": self._base_step,
            "chain_len": self._chain_len,
            "last_train_step": self._last_train_step,
        }
