"""Async, host-sharded, replicated checkpoints — the elastic format.

Reference analog: the pserver checkpoint path (checkpoint_notify_op →
each pserver persisting its own table shard) plus the etcd master snapshot
(go/master/service.go) — state survives because every owner writes its own
shard and a coordinator commits a single consistent record. Here the same
protocol is rebuilt for a ZeRO-1 / ep-sharded TPU pod as plain files:

Layout (one directory per checkpoint under a common root):

    <root>/eckpt-00000042/
        shard-00000-of-00002.npz      host 0's owned row ranges of EVERY
                                      checkpointable array (PR 8's row-range
                                      .npz layout, generalized past tables)
        shard-00000.ok.json           per-host "my shard landed" marker
        replica-00001-by-00000.npz    host 0's copy of host 1's shard — the
                                      neighbor replica: losing any ONE host
                                      (or its host-local files) loses nothing
        commit-00000.json             per-host commit marker (files + sha256)
        MANIFEST.json                 written atomically LAST by rank 0, only
                                      after every host's commit marker exists

Commit discipline (per host):
  1. slice own ranges, write shard tmp → fsync → rename → fsync dir,
     publish the `.ok` marker;
  2. wait for the RIGHT neighbor's `.ok`, byte-copy its landed shard into a
     replica file (the filesystem stands in for the replica RPC a
     host-local-storage deployment would use), verify the checksum;
  3. publish the commit marker.
Rank 0 then waits for all commit markers (the cross-host barrier) and
publishes MANIFEST.json atomically — fsyncing file and directory — so a
crash at ANY point leaves either a previous complete checkpoint or a
manifest-less directory that `latest_valid_elastic` skips.

The manifest records the topology (num_hosts/dp/ep) and per-host range plan
at save time plus a data cursor (epoch, batch index, shard seed), so
`load_elastic` can reassemble the FULL arrays on any later topology —
shard count on disk is independent of the mesh that resumes (the same
contract as embedding.EmbeddingEngine.load_sharded: the next executor run
re-places state via GSPMD).

`AsyncCheckpointer` is the training-loop face: `save()` blocks only for the
device→host copy (the measured step stall, resilience/ckpt_stall_ms) and a
daemon writer does everything else off the step path.

Incremental deltas (the online-learning format, docs/online.md): a
`eckpt-delta-%08d` directory references a base eckpt and carries only the
params that changed since its parent — dirty dense arrays whole, embedding
tables as (touched row ids, touched row values) pairs keyed `<name>` +
`<name>@rows`. Deltas form a chain base → d1 → d2 → … linked by
`parent_step`; each link reuses the shard/commit/manifest-last ladder (no
neighbor replicas: deltas are small and frequent, and losing one only costs
staleness — the base checkpoint is the durability anchor).
`resolve_delta_chain` returns the longest valid prefix, skipping a
torn/manifest-less delta the same way `latest_valid_elastic` skips torn
bases; `load_with_deltas` replays the chain into full arrays. Compaction is
the writer's job: publish a fresh base once the chain exceeds its budget,
then `gc_elastic_deltas` (manifest-first, like base GC) retires the stale
chain.
"""

import json
import os
import re
import shutil
import threading
import time
import warnings
import zlib

import numpy as np

from . import faults, health
from .checkpoint import _sha256
from .retry import DeadlineExceeded

__all__ = [
    "AsyncCheckpointer",
    "plan_host_ranges",
    "write_elastic_checkpoint",
    "verify_elastic_checkpoint",
    "latest_valid_elastic",
    "load_elastic",
    "list_elastic_checkpoints",
    "write_elastic_delta",
    "list_elastic_deltas",
    "verify_elastic_delta",
    "resolve_delta_chain",
    "apply_delta",
    "load_with_deltas",
    "gc_elastic_deltas",
]

MANIFEST = "MANIFEST.json"
_ECKPT_RE = re.compile(r"^eckpt-(\d+)$")
_DELTA_RE = re.compile(r"^eckpt-delta-(\d+)$")
# npz key suffix for a table delta's touched-row-id array; the bare key holds
# the touched rows' values. "@" keeps the pair out of any var namespace.
ROWS_KEY = "@rows"


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


def _shard_file(h, n):
    return "shard-%05d-of-%05d.npz" % (h, n)


def _shard_ok(h):
    return "shard-%05d.ok.json" % h


def _replica_file(owner, writer):
    return "replica-%05d-by-%05d.npz" % (owner, writer)


def _commit_file(h):
    return "commit-%05d.json" % h


def _fsync_dir(path):
    """Durably record a directory entry (a rename alone is not durable until
    the PARENT directory's metadata hits disk) — io.fsync_dir, imported
    lazily so this module stays import-light."""
    from .. import io as fluid_io

    fluid_io.fsync_dir(path)


def _atomic_write(path, data, binary=False):
    """tmp → write → fsync(file) → rename → fsync(dir). The full durability
    ladder: after this returns, a power cut cannot surface a torn or
    disappearing file at `path`."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb" if binary else "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


# ------------------------------------------------------------ partition plan


def plan_host_ranges(shapes, num_hosts):
    """Deterministic ownership plan: name -> [lo, hi) row range per host.

    Arrays whose leading dim can be split `num_hosts` ways get balanced
    contiguous row ranges (exactly the ZeRO-1 / ep shard a host already
    holds); smaller arrays and scalars are wholly owned by a stable-hash
    host (entry value None = "the whole array"). The plan is a pure function
    of (sorted names, shapes, num_hosts), so a restore needs only the
    manifest — never the saving process.
    """
    num_hosts = int(num_hosts)
    if num_hosts < 1:
        raise ValueError("num_hosts must be >= 1, got %d" % num_hosts)
    plans = [dict() for _ in range(num_hosts)]
    for name in sorted(shapes):
        shape = tuple(shapes[name])
        rows = shape[0] if shape else 0
        if shape and rows >= num_hosts > 1:
            for h in range(num_hosts):
                plans[h][name] = [h * rows // num_hosts,
                                  (h + 1) * rows // num_hosts]
        else:
            owner = zlib.crc32(name.encode()) % num_hosts
            plans[owner][name] = None
    return plans


def _widen(a):
    """bf16 arrays are stored as f32 (lossless widening, same trick as
    io._bf16_safe_save / EmbeddingEngine.save_sharded); returns
    (storable array, original dtype string)."""
    a = np.asarray(a)
    dt = str(a.dtype)
    if "bfloat16" in dt:
        return a.astype(np.float32), dt
    return a, dt


# ------------------------------------------------------------- write path


def _write_npz(dirname, fname, payload):
    """Atomic, durable .npz of a name->array dict, with the existing
    `ckpt_crash` hook between tmp write and rename (same fault grammar as
    io.save_arrays, so PADDLE_TPU_FAULTS=ckpt_crash:... tears elastic
    checkpoints too)."""
    path = os.path.join(dirname, fname)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    if faults.fires("ckpt_crash"):
        raise faults.InjectedFault("ckpt_crash during save of %r" % path)
    os.replace(tmp, path)
    _fsync_dir(dirname)
    return path


def _write_host_shard(dirname, host_id, num_hosts, arrays, plan_h):
    payload = {}
    for name, rng in plan_h.items():
        a, _dt = _widen(arrays[name])
        payload[name] = a if rng is None else a[rng[0]:rng[1]]
    fname = _shard_file(host_id, num_hosts)
    path = _write_npz(dirname, fname, payload)
    marker = {
        "host": host_id,
        "file": fname,
        "sha256": _sha256(path),
        "size": os.path.getsize(path),
    }
    _atomic_write(os.path.join(dirname, _shard_ok(host_id)),
                  json.dumps(marker))
    return marker


def _wait_for(path, timeout, what):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise DeadlineExceeded(
                "elastic checkpoint barrier: %s (%s) missing after %.1fs"
                % (what, path, timeout)
            )
        time.sleep(0.01)


def _write_replica(dirname, owner, writer, timeout):
    """Copy the (landed, checksummed) neighbor shard into a replica file.
    Through a shared checkpoint filesystem this is a byte copy; with
    host-local storage the same bytes would travel the replica RPC — the
    protocol (land, verify, then commit) is identical."""
    ok_path = os.path.join(dirname, _shard_ok(owner))
    _wait_for(ok_path, timeout, "shard marker of host %d" % owner)
    with open(ok_path) as f:
        marker = json.load(f)
    src = os.path.join(dirname, marker["file"])
    with open(src, "rb") as f:
        data = f.read()
    dst = _replica_file(owner, writer)
    _atomic_write(os.path.join(dirname, dst), data, binary=True)
    if _sha256(os.path.join(dirname, dst)) != marker["sha256"]:
        raise IOError(
            "replica of host %d shard failed checksum after copy" % owner
        )
    return {"file": dst, "sha256": marker["sha256"], "size": marker["size"]}


def _write_commit(dirname, host_id, files):
    faults.crash("eckpt_commit_crash", dirname)
    _atomic_write(
        os.path.join(dirname, _commit_file(host_id)),
        json.dumps({"host": host_id, "files": files}),
    )


def _wait_commit_barrier(dirname, num_hosts, timeout):
    for h in range(num_hosts):
        _wait_for(os.path.join(dirname, _commit_file(h)), timeout,
                  "commit marker of host %d" % h)


def write_elastic_checkpoint(
    root,
    arrays,
    step,
    num_hosts=1,
    host_id=0,
    cursor=None,
    topology=None,
    keep_last=3,
    barrier_timeout=None,
):
    """One host's full contribution to elastic checkpoint `step`: shard +
    neighbor replica + commit marker; rank 0 additionally runs the barrier,
    publishes the manifest, and GCs old checkpoints. Returns the checkpoint
    dir (all hosts). Synchronous — AsyncCheckpointer calls this off-thread."""
    if barrier_timeout is None:
        from .. import flags as _flags

        barrier_timeout = float(
            _flags.get_flags("elastic_barrier_timeout_s")[
                "elastic_barrier_timeout_s"]
        )
    ckpt_dir = os.path.join(root, "eckpt-%08d" % step)
    os.makedirs(ckpt_dir, exist_ok=True)
    shapes = {n: np.asarray(a).shape for n, a in arrays.items()}
    plans = plan_host_ranges(shapes, num_hosts)
    files = {}
    marker = _write_host_shard(ckpt_dir, host_id, num_hosts, arrays,
                               plans[host_id])
    files[marker["file"]] = {"sha256": marker["sha256"],
                             "size": marker["size"]}
    if num_hosts > 1:
        owner = (host_id + 1) % num_hosts
        rep = _write_replica(ckpt_dir, owner, host_id, barrier_timeout)
        files[rep["file"]] = {"sha256": rep["sha256"], "size": rep["size"]}
    _write_commit(ckpt_dir, host_id, files)
    if host_id == 0:
        _wait_commit_barrier(ckpt_dir, num_hosts, barrier_timeout)
        _publish_manifest(ckpt_dir, arrays, step, num_hosts, plans, cursor,
                          topology)
        if keep_last and keep_last > 0:
            for _s, old in list_elastic_checkpoints(root)[keep_last:]:
                # unlink the manifest FIRST (atomic): a GC killed mid-rmtree
                # must leave a manifest-less dir (skipped by recovery), never
                # a manifest whose data files are half-deleted
                try:
                    os.unlink(os.path.join(old, MANIFEST))
                except OSError:
                    pass
                shutil.rmtree(old, ignore_errors=True)
    return ckpt_dir


def _publish_manifest(ckpt_dir, arrays, step, num_hosts, plans, cursor,
                      topology):
    all_files = {}
    for h in range(num_hosts):
        with open(os.path.join(ckpt_dir, _commit_file(h))) as f:
            all_files.update(json.load(f)["files"])
    meta = {}
    for n, a in arrays.items():
        stored, orig = _widen(a)
        meta[n] = {
            "shape": list(np.asarray(a).shape),
            "dtype": orig,
            "stored_dtype": str(stored.dtype),
        }
    manifest = {
        "version": 1,
        "step": int(step),
        "num_hosts": int(num_hosts),
        "topology": dict(topology or {}),
        "cursor": dict(cursor or {}),
        "arrays": meta,
        "ranges": [
            {n: r for n, r in plan.items()} for plan in plans
        ],
        "files": all_files,
    }
    faults.crash("manifest_crash", ckpt_dir)
    _atomic_write(os.path.join(ckpt_dir, MANIFEST),
                  json.dumps(manifest, indent=1))
    try:
        now = time.time()
        _registry().counter(
            "resilience/ckpt_commits",
            help="elastic checkpoints committed (manifest published)",
        ).inc()
        _registry().gauge(
            "resilience/last_ckpt_unixtime",
            help="wall time of the last committed elastic checkpoint",
        ).set(now)
        _registry().gauge(
            "resilience/last_ckpt_step",
            help="step of the last committed elastic checkpoint",
        ).set(float(step))
    except Exception:
        pass  # observability must never fail a commit


# -------------------------------------------------------------- read path


def list_elastic_checkpoints(root):
    """[(step, dirpath)] newest first."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _ECKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort(reverse=True)
    return out


def _read_manifest(ckpt_dir):
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        return json.load(f)


def _host_source(ckpt_dir, manifest, h):
    """The readable, checksum-verified file holding host h's ranges: its
    primary shard if intact, else any replica of it — this OR is exactly the
    lose-any-one-host guarantee."""
    files = manifest["files"]
    num_hosts = manifest["num_hosts"]
    candidates = [_shard_file(h, num_hosts)] + [
        _replica_file(h, w) for w in range(num_hosts) if w != h
    ]
    for fname in candidates:
        meta = files.get(fname)
        if meta is None:
            continue
        path = os.path.join(ckpt_dir, fname)
        try:
            if (os.path.getsize(path) == meta["size"]
                    and _sha256(path) == meta["sha256"]):
                return path
        except OSError:
            continue
    return None


def verify_elastic_checkpoint(ckpt_dir):
    """True iff the manifest exists and EVERY host's ranges are recoverable
    from at least one intact file (primary or replica)."""
    try:
        manifest = _read_manifest(ckpt_dir)
    except (OSError, ValueError):
        return False
    try:
        return all(
            _host_source(ckpt_dir, manifest, h) is not None
            for h in range(manifest["num_hosts"])
        )
    except (KeyError, TypeError):
        return False


def latest_valid_elastic(root):
    """(step, dir) of the newest recoverable elastic checkpoint, or None.
    Unrecoverable candidates are counted + warned, never raised over."""
    for step, ckpt_dir in list_elastic_checkpoints(root):
        if verify_elastic_checkpoint(ckpt_dir):
            return step, ckpt_dir
        health.incr("ckpt_skipped_invalid")
        warnings.warn(
            "skipping unrecoverable elastic checkpoint %s (missing manifest "
            "or a host's ranges have neither shard nor replica)" % ckpt_dir
        )
    return None


def load_elastic(ckpt_dir):
    """Reassemble the FULL name->array dict from per-host shards, falling
    back to replicas for any host whose primary is gone. Topology-blind by
    construction: the caller overlays the full arrays into a scope and the
    next executor run re-places them onto WHATEVER mesh is live (GSPMD
    state_sharding), so a dp=N/ep=K checkpoint resumes on dp=M/ep=J.
    Returns (step, arrays, manifest)."""
    manifest = _read_manifest(ckpt_dir)
    num_hosts = manifest["num_hosts"]
    meta = manifest["arrays"]
    out = {}
    for h in range(num_hosts):
        src = _host_source(ckpt_dir, manifest, h)
        if src is None:
            raise IOError(
                "elastic checkpoint %s: host %d has neither an intact shard "
                "nor a replica — more than one host lost" % (ckpt_dir, h)
            )
        with np.load(src) as z:
            for name, rng in manifest["ranges"][h].items():
                m = meta[name]
                if name not in out:
                    out[name] = np.empty(
                        tuple(m["shape"]), dtype=np.dtype(m["stored_dtype"])
                    )
                if rng is None:
                    out[name] = np.asarray(z[name]).reshape(
                        tuple(m["shape"])
                    ).astype(np.dtype(m["stored_dtype"]))
                else:
                    out[name][rng[0]:rng[1]] = z[name]
    for name, m in meta.items():
        if "bfloat16" in m["dtype"]:
            import jax.numpy as jnp

            out[name] = jnp.asarray(out[name], dtype=jnp.bfloat16)
    return manifest["step"], out, manifest


# ------------------------------------------------------ incremental deltas


def _plan_delta(dense_shapes, rows_counts, num_hosts):
    """Ownership plan for a delta's payload keys. Dense keys reuse
    plan_host_ranges; each table's (values, @rows) pair splits over the SAME
    touched-row ranges so a host's shard is self-contained (scattering host
    h's values needs host h's ids)."""
    plans = plan_host_ranges(dense_shapes, num_hosts)
    for name in sorted(rows_counts):
        n = int(rows_counts[name])
        if n >= num_hosts > 1:
            for h in range(num_hosts):
                lo, hi = h * n // num_hosts, (h + 1) * n // num_hosts
                plans[h][name] = [lo, hi]
                plans[h][name + ROWS_KEY] = [lo, hi]
        else:
            owner = zlib.crc32(name.encode()) % num_hosts
            plans[owner][name] = None
            plans[owner][name + ROWS_KEY] = None
    return plans


def write_elastic_delta(
    root,
    step,
    base_step,
    parent_step,
    dense,
    rows=None,
    num_hosts=1,
    host_id=0,
    cursor=None,
    stamp=None,
    barrier_timeout=None,
):
    """One host's contribution to incremental delta `step` on the chain
    rooted at `base_step` (parent_step = the previous link, or base_step for
    the first delta). `dense` maps name -> full dirty array; `rows` maps
    table name -> (row_ids, row_values, full_shape). Same commit discipline
    as the base format minus the neighbor replica; rank 0 runs the barrier
    and publishes the manifest LAST, so a crash mid-write leaves a
    manifest-less dir that resolve_delta_chain skips. Returns the delta
    dir."""
    if barrier_timeout is None:
        from .. import flags as _flags

        barrier_timeout = float(
            _flags.get_flags("elastic_barrier_timeout_s")[
                "elastic_barrier_timeout_s"]
        )
    rows = rows or {}
    delta_dir = os.path.join(root, "eckpt-delta-%08d" % step)
    os.makedirs(delta_dir, exist_ok=True)
    payload = {}
    meta = {}
    rows_counts = {}
    for n, a in dense.items():
        stored, orig = _widen(a)
        payload[n] = np.asarray(a)
        meta[n] = {
            "kind": "dense",
            "shape": list(stored.shape),
            "dtype": orig,
            "stored_dtype": str(stored.dtype),
        }
    for n, (ids, vals, full_shape) in rows.items():
        ids = np.asarray(ids, dtype=np.int64)
        stored, orig = _widen(vals)
        if stored.shape[:1] != ids.shape:
            raise ValueError(
                "table %r delta: %d row values for %d row ids"
                % (n, stored.shape[0], ids.shape[0])
            )
        payload[n] = vals
        payload[n + ROWS_KEY] = ids
        rows_counts[n] = ids.shape[0]
        meta[n] = {
            "kind": "rows",
            "shape": list(full_shape),
            "dtype": orig,
            "stored_dtype": str(stored.dtype),
            "rows": int(ids.shape[0]),
        }
    dense_shapes = {n: np.asarray(a).shape for n, a in dense.items()}
    plans = _plan_delta(dense_shapes, rows_counts, num_hosts)
    files = {}
    marker = _write_host_shard(delta_dir, host_id, num_hosts, payload,
                               plans[host_id])
    files[marker["file"]] = {"sha256": marker["sha256"],
                             "size": marker["size"]}
    _write_commit(delta_dir, host_id, files)
    if host_id == 0:
        _wait_commit_barrier(delta_dir, num_hosts, barrier_timeout)
        all_files = {}
        for h in range(num_hosts):
            with open(os.path.join(delta_dir, _commit_file(h))) as f:
                all_files.update(json.load(f)["files"])
        manifest = {
            "version": 1,
            "kind": "delta",
            "step": int(step),
            "base_step": int(base_step),
            "parent_step": int(parent_step),
            "num_hosts": int(num_hosts),
            "cursor": dict(cursor or {}),
            "stamp": dict(stamp or {}),
            "arrays": meta,
            "ranges": [{n: r for n, r in plan.items()} for plan in plans],
            "files": all_files,
        }
        faults.crash("manifest_crash", delta_dir)
        _atomic_write(os.path.join(delta_dir, MANIFEST),
                      json.dumps(manifest, indent=1))
        try:
            _registry().counter(
                "resilience/delta_commits",
                help="incremental checkpoint deltas committed",
            ).inc()
        except Exception:
            pass
    return delta_dir


def list_elastic_deltas(root):
    """[(step, dirpath)] of delta dirs, newest first."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _DELTA_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort(reverse=True)
    return out


def _delta_source(delta_dir, manifest, h):
    """Host h's checksum-verified delta shard, or None. Deltas have no
    replicas — a torn shard makes the whole delta (and everything chained
    past it) unusable, which costs only staleness."""
    fname = _shard_file(h, manifest["num_hosts"])
    meta = manifest["files"].get(fname)
    if meta is None:
        return None
    path = os.path.join(delta_dir, fname)
    try:
        if (os.path.getsize(path) == meta["size"]
                and _sha256(path) == meta["sha256"]):
            return path
    except OSError:
        pass
    return None


def verify_elastic_delta(delta_dir):
    """True iff the delta's manifest exists and every host's shard is
    intact."""
    try:
        manifest = _read_manifest(delta_dir)
    except (OSError, ValueError):
        return False
    if manifest.get("kind") != "delta":
        return False
    try:
        return all(
            _delta_source(delta_dir, manifest, h) is not None
            for h in range(manifest["num_hosts"])
        )
    except (KeyError, TypeError):
        return False


def resolve_delta_chain(root, upto_step=None):
    """(base_step, base_dir, [(step, delta_dir), ...]) — the newest valid
    base at or below `upto_step` plus the longest valid chain of deltas
    rooted at it (ascending, each link's parent_step matching the previous
    step). A torn/manifest-less delta ends the chain THERE — later deltas
    reference an unusable parent — exactly the skip discipline
    latest_valid_elastic applies to torn bases. Returns None when no valid
    base exists."""
    base = None
    for step, ckpt_dir in list_elastic_checkpoints(root):
        if upto_step is not None and step > upto_step:
            continue
        if verify_elastic_checkpoint(ckpt_dir):
            base = (step, ckpt_dir)
            break
        health.incr("ckpt_skipped_invalid")
    if base is None:
        return None
    base_step, base_dir = base
    chain = []
    parent = base_step
    for step, delta_dir in sorted(list_elastic_deltas(root)):
        if step <= base_step:
            continue
        if upto_step is not None and step > upto_step:
            break
        try:
            manifest = _read_manifest(delta_dir)
        except (OSError, ValueError):
            manifest = None
        if manifest is None or not verify_elastic_delta(delta_dir):
            health.incr("delta_skipped_invalid")
            warnings.warn(
                "skipping torn/manifest-less delta %s; chain ends at step %d"
                % (delta_dir, parent)
            )
            break
        if manifest.get("base_step") != base_step:
            # a stale chain rooted at an older (or GC'd) base: not ours
            continue
        if manifest.get("parent_step") != parent:
            warnings.warn(
                "delta %s parents step %s but the chain is at %d — gap; "
                "chain ends" % (delta_dir, manifest.get("parent_step"), parent)
            )
            break
        chain.append((step, delta_dir))
        parent = step
    return base_step, base_dir, chain


def apply_delta(delta_dir, arrays):
    """Replay one delta onto a full name->array dict (from load_elastic or a
    previous apply_delta): dense entries overwrite whole arrays, table
    entries scatter touched-row values at their ids. Never mutates the input
    dict's arrays — touched tables are copied first (the reader-side
    copy-on-publish). Returns (step, new arrays dict, manifest)."""
    manifest = _read_manifest(delta_dir)
    meta = manifest["arrays"]
    num_hosts = manifest["num_hosts"]

    buffers = {}

    def _buffer(key, shape, dtype):
        if key not in buffers:
            buffers[key] = np.empty(tuple(shape), dtype=np.dtype(dtype))
        return buffers[key]

    for h in range(num_hosts):
        ranges = manifest["ranges"][h]
        if not ranges:
            continue
        src = _delta_source(delta_dir, manifest, h)
        if src is None:
            raise IOError(
                "delta %s: host %d shard missing or torn" % (delta_dir, h)
            )
        with np.load(src) as z:
            for key, rng in ranges.items():
                name = key[:-len(ROWS_KEY)] if key.endswith(ROWS_KEY) else key
                m = meta[name]
                if m["kind"] == "dense":
                    buf = _buffer(key, m["shape"], m["stored_dtype"])
                elif key.endswith(ROWS_KEY):
                    buf = _buffer(key, (m["rows"],), "int64")
                else:
                    buf = _buffer(
                        key, (m["rows"],) + tuple(m["shape"][1:]),
                        m["stored_dtype"],
                    )
                if rng is None:
                    buf[...] = np.asarray(z[key]).reshape(buf.shape)
                else:
                    buf[rng[0]:rng[1]] = z[key]

    out = dict(arrays)
    for name, m in meta.items():
        if m["kind"] == "dense":
            full = buffers[name]
            if "bfloat16" in m["dtype"]:
                import jax.numpy as jnp

                out[name] = jnp.asarray(full, dtype=jnp.bfloat16)
            else:
                out[name] = full
        else:
            if name not in out:
                raise KeyError(
                    "delta %s updates rows of %r, absent from the base"
                    % (delta_dir, name)
                )
            ids = buffers[name + ROWS_KEY]
            vals = buffers[name]
            base = np.array(np.asarray(out[name]))  # copy-on-publish
            if list(base.shape) != list(m["shape"]):
                raise ValueError(
                    "delta %s: table %r is %s on disk but %s live"
                    % (delta_dir, name, m["shape"], list(base.shape))
                )
            base[ids] = vals.astype(base.dtype)
            out[name] = base
    return manifest["step"], out, manifest


def load_with_deltas(root, upto_step=None):
    """Full arrays at the newest (or `upto_step`-bounded) published version:
    load the base eckpt, then replay its valid delta chain in order. Returns
    (step, arrays, info) where info records the chain walked and the last
    link's manifest stamp — None when no valid base exists."""
    found = resolve_delta_chain(root, upto_step=upto_step)
    if found is None:
        return None
    base_step, base_dir, chain = found
    step, arrays, manifest = load_elastic(base_dir)
    stamp = dict(manifest.get("stamp") or {})
    cursor = dict(manifest.get("cursor") or {})
    for _s, delta_dir in chain:
        step, arrays, manifest = apply_delta(delta_dir, arrays)
        stamp = dict(manifest.get("stamp") or stamp)
        cursor = dict(manifest.get("cursor") or cursor)
    info = {
        "base_step": base_step,
        "base_dir": base_dir,
        "deltas": [s for s, _ in chain],
        "stamp": stamp,
        "cursor": cursor,
    }
    return step, arrays, info


def gc_elastic_deltas(root, keep_base_step=None, before_step=None):
    """Retire delta dirs: those rooted at a different base than
    `keep_base_step` (stale chains after a compaction) and/or those at or
    below `before_step`. Manifest-first, like base GC — a GC killed
    mid-rmtree leaves a manifest-less dir the chain walk already skips.
    Returns the number of dirs removed."""
    removed = 0
    for step, delta_dir in list_elastic_deltas(root):
        stale = False
        if before_step is not None and step <= before_step:
            stale = True
        if keep_base_step is not None and not stale:
            try:
                manifest = _read_manifest(delta_dir)
                stale = manifest.get("base_step") != int(keep_base_step)
            except (OSError, ValueError):
                stale = True  # torn dir: nothing can chain through it
        if not stale:
            continue
        try:
            os.unlink(os.path.join(delta_dir, MANIFEST))
        except OSError:
            pass
        shutil.rmtree(delta_dir, ignore_errors=True)
        removed += 1
    return removed


# --------------------------------------------------------- async front-end


class AsyncCheckpointer:
    """Training-loop checkpoint front-end: `save()` stalls the step ONLY for
    the device→host copy; a daemon thread runs the shard/replica/barrier/
    manifest protocol. One save in flight at a time — a save issued while
    the writer is busy first waits for it (bounded staleness, never
    unbounded queue growth).

    A background failure is deferred and re-raised on the NEXT save()/wait()
    — a checkpoint failure must surface, but never asynchronously corrupt an
    unrelated step.
    """

    def __init__(self, root, num_hosts=1, host_id=0, keep_last=3,
                 topology=None, barrier_timeout=None):
        self.root = root
        self.num_hosts = int(num_hosts)
        self.host_id = int(host_id)
        self.keep_last = keep_last
        self.topology = dict(topology or {})
        self.barrier_timeout = barrier_timeout
        self._thread = None
        self._error = None
        self._last_commit_dir = None
        self._lock = threading.Lock()

    # -- metrics ----------------------------------------------------------
    def _observe_stall(self, ms):
        try:
            _registry().histogram(
                "resilience/ckpt_stall_ms",
                help="step-visible checkpoint stall (device->host copy for "
                     "async saves; full write for sync)",
            ).observe(ms)
            last = _registry().gauge("resilience/last_ckpt_unixtime").value()
            if last:
                _registry().gauge(
                    "resilience/last_ckpt_age_s",
                    help="seconds since the last committed elastic checkpoint",
                ).set(max(0.0, time.time() - last))
        except Exception:
            pass

    # -- lifecycle --------------------------------------------------------
    def save(self, arrays, step, cursor=None, block=False):
        """Snapshot `arrays` (name -> device/host array) to host memory NOW
        and persist in the background. Returns the step-visible stall in
        seconds. `block=True` also waits for the commit (emergency saves)."""
        self.wait()  # previous writer must finish; re-raises its failure
        t0 = time.perf_counter()
        snap = {}
        for n, a in arrays.items():
            try:
                # np.array on top of the __array__ view: on the CPU backend
                # np.asarray of a jax array is ZERO-COPY, and the background
                # writer would otherwise serialize memory that the next
                # donated step overwrites in place (on TPU the device->host
                # transfer always copies, which masked this).
                snap[n] = np.array(np.asarray(a))
            except Exception as e:  # pragma: no cover - multi-process arrays
                raise RuntimeError(
                    "cannot host-snapshot %r for the elastic checkpoint "
                    "(non-addressable multi-process array?): %s" % (n, e)
                ) from e
        stall = time.perf_counter() - t0
        self._observe_stall(stall * 1000.0)
        t = threading.Thread(
            target=self._write, args=(snap, step, cursor), daemon=True,
            name="eckpt-writer-%d" % step,
        )
        with self._lock:
            self._thread = t
        t.start()
        if block:
            self.wait()
        return stall

    def _write(self, snap, step, cursor):
        try:
            d = write_elastic_checkpoint(
                self.root, snap, step,
                num_hosts=self.num_hosts, host_id=self.host_id,
                cursor=cursor, topology=self.topology,
                keep_last=self.keep_last,
                barrier_timeout=self.barrier_timeout,
            )
            with self._lock:
                self._last_commit_dir = d
        except BaseException as e:  # deferred to the next save()/wait()
            with self._lock:
                self._error = e
            health.incr("ckpt_async_failed")

    def wait(self):
        """Join any in-flight write; raise its deferred failure."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join()
            with self._lock:
                self._thread = None
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    @property
    def last_commit_dir(self):
        with self._lock:
            return self._last_commit_dir

    def close(self):
        try:
            self.wait()
        except Exception:
            pass
