"""Async, host-sharded, replicated checkpoints — the elastic format.

Reference analog: the pserver checkpoint path (checkpoint_notify_op →
each pserver persisting its own table shard) plus the etcd master snapshot
(go/master/service.go) — state survives because every owner writes its own
shard and a coordinator commits a single consistent record. Here the same
protocol is rebuilt for a ZeRO-1 / ep-sharded TPU pod as plain files:

Layout (one directory per checkpoint under a common root):

    <root>/eckpt-00000042/
        shard-00000-of-00002.npz      host 0's owned row ranges of EVERY
                                      checkpointable array (PR 8's row-range
                                      .npz layout, generalized past tables)
        shard-00000.ok.json           per-host "my shard landed" marker
        replica-00001-by-00000.npz    host 0's copy of host 1's shard — the
                                      neighbor replica: losing any ONE host
                                      (or its host-local files) loses nothing
        commit-00000.json             per-host commit marker (files + sha256)
        MANIFEST.json                 written atomically LAST by rank 0, only
                                      after every host's commit marker exists

Commit discipline (per host):
  1. slice own ranges, write shard tmp → fsync → rename → fsync dir,
     publish the `.ok` marker;
  2. wait for the RIGHT neighbor's `.ok`, byte-copy its landed shard into a
     replica file (the filesystem stands in for the replica RPC a
     host-local-storage deployment would use), verify the checksum;
  3. publish the commit marker.
Rank 0 then waits for all commit markers (the cross-host barrier) and
publishes MANIFEST.json atomically — fsyncing file and directory — so a
crash at ANY point leaves either a previous complete checkpoint or a
manifest-less directory that `latest_valid_elastic` skips.

The manifest records the topology (num_hosts/dp/ep) and per-host range plan
at save time plus a data cursor (epoch, batch index, shard seed), so
`load_elastic` can reassemble the FULL arrays on any later topology —
shard count on disk is independent of the mesh that resumes (the same
contract as embedding.EmbeddingEngine.load_sharded: the next executor run
re-places state via GSPMD).

`AsyncCheckpointer` is the training-loop face: `save()` blocks only for the
device→host copy (the measured step stall, resilience/ckpt_stall_ms) and a
daemon writer does everything else off the step path.
"""

import json
import os
import re
import shutil
import threading
import time
import warnings
import zlib

import numpy as np

from . import faults, health
from .checkpoint import _sha256
from .retry import DeadlineExceeded

__all__ = [
    "AsyncCheckpointer",
    "plan_host_ranges",
    "write_elastic_checkpoint",
    "verify_elastic_checkpoint",
    "latest_valid_elastic",
    "load_elastic",
    "list_elastic_checkpoints",
]

MANIFEST = "MANIFEST.json"
_ECKPT_RE = re.compile(r"^eckpt-(\d+)$")


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


def _shard_file(h, n):
    return "shard-%05d-of-%05d.npz" % (h, n)


def _shard_ok(h):
    return "shard-%05d.ok.json" % h


def _replica_file(owner, writer):
    return "replica-%05d-by-%05d.npz" % (owner, writer)


def _commit_file(h):
    return "commit-%05d.json" % h


def _fsync_dir(path):
    """Durably record a directory entry (a rename alone is not durable until
    the PARENT directory's metadata hits disk) — io.fsync_dir, imported
    lazily so this module stays import-light."""
    from .. import io as fluid_io

    fluid_io.fsync_dir(path)


def _atomic_write(path, data, binary=False):
    """tmp → write → fsync(file) → rename → fsync(dir). The full durability
    ladder: after this returns, a power cut cannot surface a torn or
    disappearing file at `path`."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb" if binary else "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


# ------------------------------------------------------------ partition plan


def plan_host_ranges(shapes, num_hosts):
    """Deterministic ownership plan: name -> [lo, hi) row range per host.

    Arrays whose leading dim can be split `num_hosts` ways get balanced
    contiguous row ranges (exactly the ZeRO-1 / ep shard a host already
    holds); smaller arrays and scalars are wholly owned by a stable-hash
    host (entry value None = "the whole array"). The plan is a pure function
    of (sorted names, shapes, num_hosts), so a restore needs only the
    manifest — never the saving process.
    """
    num_hosts = int(num_hosts)
    if num_hosts < 1:
        raise ValueError("num_hosts must be >= 1, got %d" % num_hosts)
    plans = [dict() for _ in range(num_hosts)]
    for name in sorted(shapes):
        shape = tuple(shapes[name])
        rows = shape[0] if shape else 0
        if shape and rows >= num_hosts > 1:
            for h in range(num_hosts):
                plans[h][name] = [h * rows // num_hosts,
                                  (h + 1) * rows // num_hosts]
        else:
            owner = zlib.crc32(name.encode()) % num_hosts
            plans[owner][name] = None
    return plans


def _widen(a):
    """bf16 arrays are stored as f32 (lossless widening, same trick as
    io._bf16_safe_save / EmbeddingEngine.save_sharded); returns
    (storable array, original dtype string)."""
    a = np.asarray(a)
    dt = str(a.dtype)
    if "bfloat16" in dt:
        return a.astype(np.float32), dt
    return a, dt


# ------------------------------------------------------------- write path


def _write_npz(dirname, fname, payload):
    """Atomic, durable .npz of a name->array dict, with the existing
    `ckpt_crash` hook between tmp write and rename (same fault grammar as
    io.save_arrays, so PADDLE_TPU_FAULTS=ckpt_crash:... tears elastic
    checkpoints too)."""
    path = os.path.join(dirname, fname)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    if faults.fires("ckpt_crash"):
        raise faults.InjectedFault("ckpt_crash during save of %r" % path)
    os.replace(tmp, path)
    _fsync_dir(dirname)
    return path


def _write_host_shard(dirname, host_id, num_hosts, arrays, plan_h):
    payload = {}
    for name, rng in plan_h.items():
        a, _dt = _widen(arrays[name])
        payload[name] = a if rng is None else a[rng[0]:rng[1]]
    fname = _shard_file(host_id, num_hosts)
    path = _write_npz(dirname, fname, payload)
    marker = {
        "host": host_id,
        "file": fname,
        "sha256": _sha256(path),
        "size": os.path.getsize(path),
    }
    _atomic_write(os.path.join(dirname, _shard_ok(host_id)),
                  json.dumps(marker))
    return marker


def _wait_for(path, timeout, what):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise DeadlineExceeded(
                "elastic checkpoint barrier: %s (%s) missing after %.1fs"
                % (what, path, timeout)
            )
        time.sleep(0.01)


def _write_replica(dirname, owner, writer, timeout):
    """Copy the (landed, checksummed) neighbor shard into a replica file.
    Through a shared checkpoint filesystem this is a byte copy; with
    host-local storage the same bytes would travel the replica RPC — the
    protocol (land, verify, then commit) is identical."""
    ok_path = os.path.join(dirname, _shard_ok(owner))
    _wait_for(ok_path, timeout, "shard marker of host %d" % owner)
    with open(ok_path) as f:
        marker = json.load(f)
    src = os.path.join(dirname, marker["file"])
    with open(src, "rb") as f:
        data = f.read()
    dst = _replica_file(owner, writer)
    _atomic_write(os.path.join(dirname, dst), data, binary=True)
    if _sha256(os.path.join(dirname, dst)) != marker["sha256"]:
        raise IOError(
            "replica of host %d shard failed checksum after copy" % owner
        )
    return {"file": dst, "sha256": marker["sha256"], "size": marker["size"]}


def _write_commit(dirname, host_id, files):
    faults.crash("eckpt_commit_crash", dirname)
    _atomic_write(
        os.path.join(dirname, _commit_file(host_id)),
        json.dumps({"host": host_id, "files": files}),
    )


def _wait_commit_barrier(dirname, num_hosts, timeout):
    for h in range(num_hosts):
        _wait_for(os.path.join(dirname, _commit_file(h)), timeout,
                  "commit marker of host %d" % h)


def write_elastic_checkpoint(
    root,
    arrays,
    step,
    num_hosts=1,
    host_id=0,
    cursor=None,
    topology=None,
    keep_last=3,
    barrier_timeout=None,
):
    """One host's full contribution to elastic checkpoint `step`: shard +
    neighbor replica + commit marker; rank 0 additionally runs the barrier,
    publishes the manifest, and GCs old checkpoints. Returns the checkpoint
    dir (all hosts). Synchronous — AsyncCheckpointer calls this off-thread."""
    if barrier_timeout is None:
        from .. import flags as _flags

        barrier_timeout = float(
            _flags.get_flags("elastic_barrier_timeout_s")[
                "elastic_barrier_timeout_s"]
        )
    ckpt_dir = os.path.join(root, "eckpt-%08d" % step)
    os.makedirs(ckpt_dir, exist_ok=True)
    shapes = {n: np.asarray(a).shape for n, a in arrays.items()}
    plans = plan_host_ranges(shapes, num_hosts)
    files = {}
    marker = _write_host_shard(ckpt_dir, host_id, num_hosts, arrays,
                               plans[host_id])
    files[marker["file"]] = {"sha256": marker["sha256"],
                             "size": marker["size"]}
    if num_hosts > 1:
        owner = (host_id + 1) % num_hosts
        rep = _write_replica(ckpt_dir, owner, host_id, barrier_timeout)
        files[rep["file"]] = {"sha256": rep["sha256"], "size": rep["size"]}
    _write_commit(ckpt_dir, host_id, files)
    if host_id == 0:
        _wait_commit_barrier(ckpt_dir, num_hosts, barrier_timeout)
        _publish_manifest(ckpt_dir, arrays, step, num_hosts, plans, cursor,
                          topology)
        if keep_last and keep_last > 0:
            for _s, old in list_elastic_checkpoints(root)[keep_last:]:
                # unlink the manifest FIRST (atomic): a GC killed mid-rmtree
                # must leave a manifest-less dir (skipped by recovery), never
                # a manifest whose data files are half-deleted
                try:
                    os.unlink(os.path.join(old, MANIFEST))
                except OSError:
                    pass
                shutil.rmtree(old, ignore_errors=True)
    return ckpt_dir


def _publish_manifest(ckpt_dir, arrays, step, num_hosts, plans, cursor,
                      topology):
    all_files = {}
    for h in range(num_hosts):
        with open(os.path.join(ckpt_dir, _commit_file(h))) as f:
            all_files.update(json.load(f)["files"])
    meta = {}
    for n, a in arrays.items():
        stored, orig = _widen(a)
        meta[n] = {
            "shape": list(np.asarray(a).shape),
            "dtype": orig,
            "stored_dtype": str(stored.dtype),
        }
    manifest = {
        "version": 1,
        "step": int(step),
        "num_hosts": int(num_hosts),
        "topology": dict(topology or {}),
        "cursor": dict(cursor or {}),
        "arrays": meta,
        "ranges": [
            {n: r for n, r in plan.items()} for plan in plans
        ],
        "files": all_files,
    }
    faults.crash("manifest_crash", ckpt_dir)
    _atomic_write(os.path.join(ckpt_dir, MANIFEST),
                  json.dumps(manifest, indent=1))
    try:
        now = time.time()
        _registry().counter(
            "resilience/ckpt_commits",
            help="elastic checkpoints committed (manifest published)",
        ).inc()
        _registry().gauge(
            "resilience/last_ckpt_unixtime",
            help="wall time of the last committed elastic checkpoint",
        ).set(now)
        _registry().gauge(
            "resilience/last_ckpt_step",
            help="step of the last committed elastic checkpoint",
        ).set(float(step))
    except Exception:
        pass  # observability must never fail a commit


# -------------------------------------------------------------- read path


def list_elastic_checkpoints(root):
    """[(step, dirpath)] newest first."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _ECKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort(reverse=True)
    return out


def _read_manifest(ckpt_dir):
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        return json.load(f)


def _host_source(ckpt_dir, manifest, h):
    """The readable, checksum-verified file holding host h's ranges: its
    primary shard if intact, else any replica of it — this OR is exactly the
    lose-any-one-host guarantee."""
    files = manifest["files"]
    num_hosts = manifest["num_hosts"]
    candidates = [_shard_file(h, num_hosts)] + [
        _replica_file(h, w) for w in range(num_hosts) if w != h
    ]
    for fname in candidates:
        meta = files.get(fname)
        if meta is None:
            continue
        path = os.path.join(ckpt_dir, fname)
        try:
            if (os.path.getsize(path) == meta["size"]
                    and _sha256(path) == meta["sha256"]):
                return path
        except OSError:
            continue
    return None


def verify_elastic_checkpoint(ckpt_dir):
    """True iff the manifest exists and EVERY host's ranges are recoverable
    from at least one intact file (primary or replica)."""
    try:
        manifest = _read_manifest(ckpt_dir)
    except (OSError, ValueError):
        return False
    try:
        return all(
            _host_source(ckpt_dir, manifest, h) is not None
            for h in range(manifest["num_hosts"])
        )
    except (KeyError, TypeError):
        return False


def latest_valid_elastic(root):
    """(step, dir) of the newest recoverable elastic checkpoint, or None.
    Unrecoverable candidates are counted + warned, never raised over."""
    for step, ckpt_dir in list_elastic_checkpoints(root):
        if verify_elastic_checkpoint(ckpt_dir):
            return step, ckpt_dir
        health.incr("ckpt_skipped_invalid")
        warnings.warn(
            "skipping unrecoverable elastic checkpoint %s (missing manifest "
            "or a host's ranges have neither shard nor replica)" % ckpt_dir
        )
    return None


def load_elastic(ckpt_dir):
    """Reassemble the FULL name->array dict from per-host shards, falling
    back to replicas for any host whose primary is gone. Topology-blind by
    construction: the caller overlays the full arrays into a scope and the
    next executor run re-places them onto WHATEVER mesh is live (GSPMD
    state_sharding), so a dp=N/ep=K checkpoint resumes on dp=M/ep=J.
    Returns (step, arrays, manifest)."""
    manifest = _read_manifest(ckpt_dir)
    num_hosts = manifest["num_hosts"]
    meta = manifest["arrays"]
    out = {}
    for h in range(num_hosts):
        src = _host_source(ckpt_dir, manifest, h)
        if src is None:
            raise IOError(
                "elastic checkpoint %s: host %d has neither an intact shard "
                "nor a replica — more than one host lost" % (ckpt_dir, h)
            )
        with np.load(src) as z:
            for name, rng in manifest["ranges"][h].items():
                m = meta[name]
                if name not in out:
                    out[name] = np.empty(
                        tuple(m["shape"]), dtype=np.dtype(m["stored_dtype"])
                    )
                if rng is None:
                    out[name] = np.asarray(z[name]).reshape(
                        tuple(m["shape"])
                    ).astype(np.dtype(m["stored_dtype"]))
                else:
                    out[name][rng[0]:rng[1]] = z[name]
    for name, m in meta.items():
        if "bfloat16" in m["dtype"]:
            import jax.numpy as jnp

            out[name] = jnp.asarray(out[name], dtype=jnp.bfloat16)
    return manifest["step"], out, manifest


# --------------------------------------------------------- async front-end


class AsyncCheckpointer:
    """Training-loop checkpoint front-end: `save()` stalls the step ONLY for
    the device→host copy; a daemon thread runs the shard/replica/barrier/
    manifest protocol. One save in flight at a time — a save issued while
    the writer is busy first waits for it (bounded staleness, never
    unbounded queue growth).

    A background failure is deferred and re-raised on the NEXT save()/wait()
    — a checkpoint failure must surface, but never asynchronously corrupt an
    unrelated step.
    """

    def __init__(self, root, num_hosts=1, host_id=0, keep_last=3,
                 topology=None, barrier_timeout=None):
        self.root = root
        self.num_hosts = int(num_hosts)
        self.host_id = int(host_id)
        self.keep_last = keep_last
        self.topology = dict(topology or {})
        self.barrier_timeout = barrier_timeout
        self._thread = None
        self._error = None
        self._last_commit_dir = None
        self._lock = threading.Lock()

    # -- metrics ----------------------------------------------------------
    def _observe_stall(self, ms):
        try:
            _registry().histogram(
                "resilience/ckpt_stall_ms",
                help="step-visible checkpoint stall (device->host copy for "
                     "async saves; full write for sync)",
            ).observe(ms)
            last = _registry().gauge("resilience/last_ckpt_unixtime").value()
            if last:
                _registry().gauge(
                    "resilience/last_ckpt_age_s",
                    help="seconds since the last committed elastic checkpoint",
                ).set(max(0.0, time.time() - last))
        except Exception:
            pass

    # -- lifecycle --------------------------------------------------------
    def save(self, arrays, step, cursor=None, block=False):
        """Snapshot `arrays` (name -> device/host array) to host memory NOW
        and persist in the background. Returns the step-visible stall in
        seconds. `block=True` also waits for the commit (emergency saves)."""
        self.wait()  # previous writer must finish; re-raises its failure
        t0 = time.perf_counter()
        snap = {}
        for n, a in arrays.items():
            try:
                snap[n] = np.asarray(a)
            except Exception as e:  # pragma: no cover - multi-process arrays
                raise RuntimeError(
                    "cannot host-snapshot %r for the elastic checkpoint "
                    "(non-addressable multi-process array?): %s" % (n, e)
                ) from e
        stall = time.perf_counter() - t0
        self._observe_stall(stall * 1000.0)
        t = threading.Thread(
            target=self._write, args=(snap, step, cursor), daemon=True,
            name="eckpt-writer-%d" % step,
        )
        with self._lock:
            self._thread = t
        t.start()
        if block:
            self.wait()
        return stall

    def _write(self, snap, step, cursor):
        try:
            d = write_elastic_checkpoint(
                self.root, snap, step,
                num_hosts=self.num_hosts, host_id=self.host_id,
                cursor=cursor, topology=self.topology,
                keep_last=self.keep_last,
                barrier_timeout=self.barrier_timeout,
            )
            with self._lock:
                self._last_commit_dir = d
        except BaseException as e:  # deferred to the next save()/wait()
            with self._lock:
                self._error = e
            health.incr("ckpt_async_failed")

    def wait(self):
        """Join any in-flight write; raise its deferred failure."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join()
            with self._lock:
                self._thread = None
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    @property
    def last_commit_dir(self):
        with self._lock:
            return self._last_commit_dir

    def close(self):
        try:
            self.wait()
        except Exception:
            pass
