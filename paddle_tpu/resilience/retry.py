"""Unified retry policy: bounded attempts, exponential backoff with jitter,
an overall deadline, and typed retryable-vs-fatal errors.

Reference analog: grpc_client.cc retried every RPC FLAGS_max_retry times
under FLAGS_rpc_deadline; the master client and the NCCL-id rendezvous each
had their own ad-hoc loops. Here one policy object expresses all of them:

    policy = RetryPolicy(max_attempts=4, base_delay=0.1, deadline=120.0)
    reply = policy.call(send_once)

Error typing contract:
- FatalError (or anything in `fatal`) aborts immediately — e.g. an RPC whose
  bytes may already have reached the server must not be resent.
- DeadlineExceeded is a TimeoutError: a hung peer surfaces as a typed,
  catchable error instead of an indefinite block.
- anything in `retryable` is retried until attempts or the deadline run out,
  then the LAST error is re-raised (types survive: callers still catch
  ConnectionError/TimeoutError exactly as before).
"""

import time
from random import Random

__all__ = ["RetryPolicy", "DeadlineExceeded", "FatalError"]


class FatalError(Exception):
    """Never retried. Wrap a cause with `FatalError(str(e))` + `from e`, or
    list domain exception types in RetryPolicy(fatal=...)."""


class DeadlineExceeded(TimeoutError):
    """A connect/read deadline or an overall retry deadline expired.
    TimeoutError => also an OSError, so pre-existing `except OSError`
    cleanup paths keep working.

    When raised by RetryPolicy.call the instance carries ``attempts`` — a
    list of ``(attempt_index, repr(error))`` pairs for every try made before
    the budget ran out — so the caller's error report (and the fleet
    router's 504 body) can show WHAT kept failing, not just that time ran
    out."""

    def __init__(self, *args):
        super().__init__(*args)
        self.attempts = []


class RetryPolicy:
    """One retryable call: `policy.call(fn)` runs fn up to max_attempts
    times, sleeping base_delay * multiplier**i (capped at max_delay, +/- a
    jitter fraction) between attempts, never past `deadline` seconds total.

    jitter="decorrelated" switches to decorrelated jitter: each pause is
    uniform(base_delay, 3 * previous pause), capped at max_delay. Unlike the
    +/-fraction form — where N processes sharing attempt counts stay packed
    in a narrow band around the same exponential schedule — successive draws
    diverge, so a fleet of hosts that failed together (every worker retrying
    `jax.distributed.initialize` against a coordinator that isn't up yet)
    spreads out instead of thundering back in lockstep.

    `seed` makes the jitter sequence deterministic (resilience tests);
    `sleep` is injectable for zero-wall-clock unit tests.
    """

    def __init__(
        self,
        max_attempts=4,
        base_delay=0.1,
        max_delay=2.0,
        multiplier=2.0,
        jitter=0.25,
        deadline=None,
        retryable=(ConnectionError, TimeoutError, OSError, EOFError),
        fatal=(FatalError,),
        seed=None,
        sleep=time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline = deadline
        self.retryable = tuple(retryable)
        self.fatal = tuple(fatal)
        self._seed = seed
        self._rng = Random(seed)
        self._sleep = sleep
        self._prev = None  # decorrelated mode: last pause issued

    def with_deadline(self, budget_s):
        """A copy of this policy whose total time budget is `budget_s`
        seconds — the caller's REMAINING deadline, not a fresh one. The copy
        stops retrying the moment the next backoff pause would overrun the
        budget, raising DeadlineExceeded with the attempt history attached
        (``.attempts``). A zero/negative budget still allows exactly one
        attempt: the budget gates retries, never the first try.

        The copy has fresh jitter state (same seed), so handing one template
        policy to many concurrent requests stays race-free — each request
        derives its own."""
        return RetryPolicy(
            max_attempts=self.max_attempts,
            base_delay=self.base_delay,
            max_delay=self.max_delay,
            multiplier=self.multiplier,
            jitter=self.jitter,
            deadline=max(float(budget_s), 0.0),
            retryable=self.retryable,
            fatal=self.fatal,
            seed=self._seed,
            sleep=self._sleep,
        )

    def backoff(self, attempt):
        """Delay before retrying after 0-based `attempt` (jittered)."""
        if self.jitter == "decorrelated":
            prev = self._prev if self._prev is not None else self.base_delay
            d = self._rng.uniform(self.base_delay, max(prev * 3.0,
                                                       self.base_delay))
            d = min(d, self.max_delay)
            self._prev = d
            return max(d, 0.0)
        d = min(self.base_delay * (self.multiplier ** attempt), self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(d, 0.0)

    def call(self, fn, *args, on_retry=None, **kwargs):
        """Run fn(*args, **kwargs) under this policy. `on_retry(attempt, err)`
        is invoked before each backoff sleep (logging/metrics hook)."""
        start = time.monotonic()
        last = None
        history = []
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.fatal:
                raise
            except self.retryable as e:
                last = e
                history.append((attempt, repr(e)))
                if attempt + 1 >= self.max_attempts:
                    break
                pause = self.backoff(attempt)
                if self.deadline is not None:
                    remaining = self.deadline - (time.monotonic() - start)
                    if remaining <= pause:
                        err = DeadlineExceeded(
                            "retry deadline %.1fs exhausted after %d attempts"
                            % (self.deadline, attempt + 1)
                        )
                        err.attempts = history
                        raise err from e
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(pause)
        raise last
