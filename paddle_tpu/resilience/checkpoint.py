"""Crash-safe, manifest-based checkpoints.

Layout (one directory per checkpoint under a common root):

    <root>/ckpt-00000012/
        <var>.npy            tensor payloads (io.save_arrays layout, so a
        <var>.npy.dtype      checkpoint is also readable by io.load_arrays)
        MANIFEST.json        {"version": 1, "step": 12,
                              "files": {"<rel>": {"sha256": ..., "size": ...}}}

Commit discipline: tensor files land first (each one atomically AND durably
— write-temp, fsync, rename, then one fsync of the containing directory;
io.save_arrays), the MANIFEST is written atomically LAST with the same
file-fsync → rename → dir-fsync ladder. A crash OR power cut at any point
leaves either a previous complete checkpoint untouched, or a manifest-less /
checksum-mismatched directory that load_latest_valid skips. This is the same ordering the reference's etcd
master snapshot relied on (go/master/service.go:166-207: state blob committed
in one txn), generalized to a directory of tensors.

`resume_or_init` is the trainer-loop entry: run the startup program, then
overlay the latest valid checkpoint if one exists.
"""

import hashlib
import json
import os
import re
import shutil
import warnings

import numpy as np

from . import faults, health

__all__ = [
    "save_checkpoint",
    "load_latest_valid",
    "latest_valid_dir",
    "resume_or_init",
    "snapshot_persistables",
    "verify_checkpoint",
]

MANIFEST = "MANIFEST.json"
_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _list_checkpoints(root):
    """[(step, dirpath)] newest first."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort(reverse=True)
    return out


def save_checkpoint(root, arrays, step, keep_last=3):
    """Write `arrays` (name -> array) as checkpoint `step` under `root`;
    returns the checkpoint directory. Old checkpoints beyond the newest
    `keep_last` are deleted AFTER the new manifest commits, so GC can never
    leave fewer than one valid checkpoint behind."""
    ckpt_dir = os.path.join(root, "ckpt-%08d" % step)
    if os.path.isdir(ckpt_dir):
        # a previous crashed/duplicate attempt at this step: rewrite cleanly
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    from .. import io as fluid_io

    fluid_io.save_arrays(ckpt_dir, arrays)  # carries the ckpt_crash hook
    files = {}
    for dirpath, _dirs, fnames in os.walk(ckpt_dir):
        for fname in sorted(fnames):
            if fname == MANIFEST or ".tmp." in fname:
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, ckpt_dir)
            files[rel] = {"sha256": _sha256(path), "size": os.path.getsize(path)}
    faults.crash("manifest_crash", ckpt_dir)
    manifest = {"version": 1, "step": int(step), "files": files}
    from .. import io as fluid_io

    # durability ordering: every data file AND the directory entries must be
    # on disk BEFORE the manifest publishes (save_arrays fsyncs both), and
    # the manifest itself gets file-fsync → rename → dir-fsync — otherwise a
    # power cut after the rename can surface a manifest whose directory
    # entry survived but whose payload renames rolled back (a "valid"-
    # looking, unreadable checkpoint)
    tmp = os.path.join(ckpt_dir, "%s.tmp.%d" % (MANIFEST, os.getpid()))
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(ckpt_dir, MANIFEST))
    fluid_io.fsync_dir(ckpt_dir)
    if keep_last and keep_last > 0:
        for _s, old in _list_checkpoints(root)[keep_last:]:
            # manifest goes first (atomic unlink): a GC killed mid-rmtree
            # leaves a manifest-less dir that recovery skips, never a
            # manifest over half-deleted payload files
            try:
                os.unlink(os.path.join(old, MANIFEST))
            except OSError:
                pass
            shutil.rmtree(old, ignore_errors=True)
    return ckpt_dir


def verify_checkpoint(ckpt_dir):
    """True iff the manifest exists and every listed file matches its
    recorded size + sha256 (torn/partial checkpoints fail here)."""
    try:
        with open(os.path.join(ckpt_dir, MANIFEST)) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError):
        return False
    for rel, meta in files.items():
        path = os.path.join(ckpt_dir, rel)
        try:
            if os.path.getsize(path) != meta["size"]:
                return False
            if _sha256(path) != meta["sha256"]:
                return False
        except (OSError, KeyError):
            return False
    return True


def latest_valid_dir(root):
    """Newest checkpoint dir that verifies, or None. Invalid candidates are
    counted + warned, never raised over — that is the point."""
    for step, ckpt_dir in _list_checkpoints(root):
        if verify_checkpoint(ckpt_dir):
            return step, ckpt_dir
        health.incr("ckpt_skipped_invalid")
        warnings.warn(
            "skipping invalid/torn checkpoint %s (no manifest or checksum "
            "mismatch)" % ckpt_dir
        )
    return None


def load_latest_valid(root):
    """(step, name->array) of the newest consistent checkpoint, or None."""
    found = latest_valid_dir(root)
    if found is None:
        return None
    step, ckpt_dir = found
    from .. import io as fluid_io

    return step, fluid_io.load_arrays(ckpt_dir)


def snapshot_persistables(program, scope=None):
    """Host-side name->array snapshot of the program's persistable state
    (params, optimizer accumulators, lr) — the checkpointable set. Gradient
    staging names (`*@GRAD` etc.) are transient and skipped, like
    save_persistables. Copied to host NOW (np.array, not the zero-copy
    np.asarray view the CPU backend hands back), so a later donated
    in-place step cannot mutate the snapshot."""
    from ..executor import global_scope
    from ..io import _is_persistable

    scope = scope or global_scope()
    out = {}
    for v in program.list_vars():
        if not _is_persistable(v) or "@" in v.name:
            continue
        val = scope.find_var(v.name)
        if val is not None:
            out[v.name] = np.array(np.asarray(val))
    return out


def resume_or_init(exe, startup_program, root, scope=None, program=None):
    """Trainer-loop entry: run the startup program, then overlay the latest
    valid checkpoint from `root` (if any) onto the scope. Returns the number
    of completed steps recorded in that checkpoint — 0 for a fresh start —
    i.e. the index the training loop resumes from.

    `program` optionally restricts the restore to names that program knows
    (a checkpoint written by a wider program must not leak foreign vars
    into this scope)."""
    import jax.numpy as jnp

    from ..executor import global_scope

    exe.run(startup_program)
    found = load_latest_valid(root)
    if found is None:
        return 0
    step, arrays = found
    scope = scope or global_scope()
    allowed = None
    if program is not None:
        allowed = {v.name for v in program.list_vars()}
    for name, arr in arrays.items():
        if allowed is None or name in allowed:
            # copy, not zero-copy wrap — see resilience/elastic.py _overlay
            scope.set_var(name, jnp.array(arr))
    health.incr("resumed_from_checkpoint")
    return step
