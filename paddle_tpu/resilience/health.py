"""Process-wide health counters for degraded-but-alive events.

Surviving a fault silently is almost as bad as dying from it: operators need
to see that a run skipped 3 NaN steps and retried 40 RPCs. Counters are a
plain thread-safe name->int map; runners print `snapshot()` at exit (the
dist test runners emit it as a HEALTH json line).

Well-known counter names (incremented by the wired hook points):
  nan_steps_skipped   executor NaN/Inf step guard fired
  lr_decays           guard decayed the learning rate / loss scale
  rpc_retries         RPCClient retried a call
  master_retries      MasterClient retried a call
  dist_init_retries   multihost.init_distributed retried the rendezvous
  master_snapshot_corrupt   Master started fresh over a bad snapshot
  ckpt_skipped_invalid      load_latest_valid skipped a torn checkpoint
"""

import threading

__all__ = ["incr", "get", "snapshot", "reset"]

_lock = threading.Lock()
_counters = {}


def incr(name, n=1):
    with _lock:
        _counters[name] = _counters.get(name, 0) + n
        return _counters[name]


def get(name):
    with _lock:
        return _counters.get(name, 0)


def snapshot():
    with _lock:
        return dict(_counters)


def reset():
    with _lock:
        _counters.clear()
