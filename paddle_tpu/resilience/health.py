"""Process-wide health counters for degraded-but-alive events.

Surviving a fault silently is almost as bad as dying from it: operators need
to see that a run skipped 3 NaN steps and retried 40 RPCs. The incr/get/
snapshot/reset API is unchanged since PR 1, but the storage now lives in the
shared observability metric registry (observability/registry.py) as counters
named "health/<name>" — so the same events ride the telemetry JSONL and
Prometheus exports (FLAGS_telemetry_dir), appear in the periodic health line
(FLAGS_telemetry_log_every), and render in tools/monitor.py, while the
runners keep printing `snapshot()` at exit exactly as before.

Well-known counter names (incremented by the wired hook points):
  nan_steps_skipped   executor NaN/Inf step guard fired
  lr_decays           guard decayed the learning rate / loss scale
  rpc_retries         RPCClient retried a call
  master_retries      MasterClient retried a call
  dist_init_retries   multihost.init_distributed retried the rendezvous
  master_snapshot_corrupt   Master started fresh over a bad snapshot
  ckpt_skipped_invalid      load_latest_valid skipped a torn checkpoint
"""

from ..observability import registry as _registry

__all__ = ["incr", "get", "snapshot", "reset"]

_PREFIX = "health/"


def _reg():
    return _registry.default_registry()


def incr(name, n=1):
    return int(_reg().counter(_PREFIX + name).inc(n))


def get(name):
    m = _reg().get(_PREFIX + name)
    return int(m.value()) if m is not None else 0


def snapshot():
    """{name: count} of every counter incremented since the last reset —
    same contract as the original plain-dict implementation (a counter
    exists only once incr'd, so reset() -> snapshot() == {})."""
    reg = _reg()
    out = {}
    for full in reg.names(_PREFIX):
        m = reg.get(full)
        if m is not None and m.kind == "counter":
            out[full[len(_PREFIX):]] = int(m.value())
    return out


def reset():
    _reg().reset(_PREFIX)
