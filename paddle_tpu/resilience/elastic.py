"""Elastic training supervision: watchdog, NaN escalation, preemption drain,
and topology-changing resume.

Reference analog: the Go master's trainer elasticity — tasks lease-timeout
back into a todo queue when a trainer dies (go/master/service.go), etcd
snapshots make the master itself restartable, and the fault-tolerant mode's
trainers could join/leave freely. Here the same survival contract wraps the
SPMD training loop:

- `Supervisor.run_step` brackets `Executor.run` with a step-deadline
  watchdog (hang → health counter → emergency checkpoint → FatalError),
  escalates NaN storms past the executor's single-step guard by rolling
  back to the last committed elastic checkpoint under a bounded retry
  budget, and turns SIGTERM (or `PADDLE_TPU_FAULTS=preempt`) into an
  emergency snapshot + clean data drain + typed `Preempted` exit.
- `resume_or_init` restores from the newest recoverable checkpoint in
  EITHER format (elastic eckpt-* preferred, PR 1 ckpt-* as fallback) and
  returns the manifest's data cursor, so the loop resumes exactly-once on
  data as well as state.
- The restore is topology-blind: `async_ckpt.load_elastic` reassembles full
  arrays from shards + replicas, the overlay lands them in the scope, and
  the next executor run re-places them onto whatever mesh is live (GSPMD
  state_sharding) — a dp=N/ep=K checkpoint resumes on dp=M/ep=J.
  `derive_data_shards` re-derives the matching data assignment for the new
  host count from the cursor's (seed, epoch) via data/sharding's pure
  functions.

See docs/resilience.md for the drain semantics and topology-resume matrix.
"""

import os
import signal
import threading
import time

import numpy as np

from . import async_ckpt, checkpoint, faults, health
from .retry import FatalError

__all__ = [
    "Supervisor",
    "Preempted",
    "resume_or_init",
    "derive_data_shards",
    "heartbeat",
]


class Preempted(Exception):
    """Raised by Supervisor after a CLEAN preemption exit: the emergency
    checkpoint committed and the data runtime drained. Exiting 0 on this is
    correct — the next incarnation resumes from the manifest."""


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


def _flag(name):
    from .. import flags as _flags

    return _flags.get_flags(name)[name]


# ----------------------------------------------------------- heartbeat bus

_watchers = []
_watchers_lock = threading.Lock()


def heartbeat():
    """Progress beat consulted by the step-deadline watchdog. Executor.run
    calls this at every entry — module-level so the executor never needs a
    Supervisor reference, and a no-op (one list probe) when no watchdog is
    installed."""
    if _watchers:
        now = time.monotonic()
        with _watchers_lock:
            for w in _watchers:
                w.beat(now)


class _Watchdog:
    """Step-deadline monitor: while a supervised step is in flight, a daemon
    thread checks that a heartbeat arrived within `deadline_s`. Detection is
    a flag the Supervisor acts on when (if) the step returns — the watchdog
    itself never mutates training state from its thread; it only counts and,
    for a truly wedged process, leaves the operator a health record."""

    def __init__(self, deadline_s):
        self.deadline = float(deadline_s)
        self._beat = time.monotonic()
        self._in_step = False
        self._stalled = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="elastic-watchdog"
        )

    def start(self):
        with _watchers_lock:
            _watchers.append(self)
        self._thread.start()

    def stop(self):
        self._stop.set()
        with _watchers_lock:
            if self in _watchers:
                _watchers.remove(self)

    def beat(self, now=None):
        self._beat = now if now is not None else time.monotonic()

    def begin_step(self):
        self._stalled = False
        self.beat()
        self._in_step = True

    def end_step(self):
        self._in_step = False
        return self._stalled

    def _loop(self):
        poll = max(0.01, self.deadline / 4.0)
        while not self._stop.wait(poll):
            if not self._in_step or self._stalled:
                continue
            if time.monotonic() - self._beat > self.deadline:
                self._stalled = True
                health.incr("watchdog_stalls")
                from ..observability import flightrec as _flightrec

                _flightrec.trigger("watchdog_stall", deadline_s=self.deadline)
                try:
                    _registry().counter(
                        "resilience/watchdog_stalls",
                        help="steps that exceeded the elastic step deadline",
                    ).inc()
                except Exception:
                    pass


# ------------------------------------------------------------- supervision


class Supervisor:
    """Wraps a trainer loop's `Executor.run` calls with the elastic survival
    contract. Typical use (tests/elastic_runner.py):

        sup = Supervisor(exe, ckpt_root, program=main_prog,
                         num_hosts=H, host_id=h, ckpt_every=10)
        step, cursor = sup.resume_or_init(startup_prog)
        with sup:                       # installs SIGTERM handler + watchdog
            for step in range(step, total):
                loss, = sup.run_step(program=main_prog, feed=batch(step),
                                     fetch_list=[loss_var])

    `ckpt_every=0` disables periodic saves (the caller drives `save()`).
    Deadlines/budgets default from FLAGS_elastic_* (flags.py).
    """

    def __init__(
        self,
        exe,
        root,
        program=None,
        scope=None,
        num_hosts=1,
        host_id=0,
        topology=None,
        ckpt_every=0,
        keep_last=3,
        reader=None,
        step_deadline_s=None,
        nan_budget=None,
        rollback_budget=None,
        checkpointer=None,
    ):
        self.exe = exe
        self.root = root
        self.program = program
        self.scope = scope
        self.num_hosts = int(num_hosts)
        self.host_id = int(host_id)
        self.ckpt_every = int(ckpt_every)
        self.reader = reader
        self.step_deadline_s = (
            float(step_deadline_s) if step_deadline_s is not None
            else float(_flag("elastic_step_deadline_s"))
        )
        self.nan_budget = (
            int(nan_budget) if nan_budget is not None
            else int(_flag("elastic_nan_budget"))
        )
        self.rollback_budget = (
            int(rollback_budget) if rollback_budget is not None
            else int(_flag("elastic_rollback_budget"))
        )
        if topology is None and hasattr(exe, "topology"):
            topology = exe.topology
        self.topology = dict(topology or {})
        self.checkpointer = checkpointer or async_ckpt.AsyncCheckpointer(
            root, num_hosts=self.num_hosts, host_id=self.host_id,
            keep_last=keep_last, topology=self.topology,
        )
        self.step = 0
        self.cursor = {"epoch": 0, "batch_index": 0, "seed": 0}
        self._preempt = False
        self._bad_steps = 0
        self._rollbacks = 0
        self._watchdog = None
        self._prev_sigterm = None
        self._nan_base = health.get("nan_steps_skipped")

    # ---------------------------------------------------------- lifecycle
    def __enter__(self):
        if self.step_deadline_s > 0:
            self._watchdog = _Watchdog(self.step_deadline_s)
            self._watchdog.start()
        # SIGTERM is the cloud's preemption notice (and the `preempt` fault
        # kind's delivery vehicle); only the main thread may install
        try:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm
            )
        except ValueError:
            self._prev_sigterm = None
        return self

    def __exit__(self, *exc):
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        self.checkpointer.close()
        return False

    def _on_sigterm(self, signum, frame):
        self._preempt = True
        health.incr("preempt_signals")

    # ------------------------------------------------------------- state
    def _state(self):
        """name -> live scope value for every checkpointable var of the
        supervised program (persistables minus gradient staging, the
        save_persistables set). Values stay as device arrays here — the
        host copy happens inside AsyncCheckpointer.save, where it is the
        measured stall."""
        from ..executor import global_scope
        from ..io import _is_persistable

        if self.program is None:
            raise ValueError("Supervisor needs `program=` to checkpoint")
        scope = self.scope or global_scope()
        out = {}
        for v in self.program.list_vars():
            if not _is_persistable(v) or "@" in v.name:
                continue
            val = scope.find_var(v.name)
            if val is not None:
                out[v.name] = val
        return out

    def save(self, block=False):
        """Checkpoint now (async unless block). Records step + data cursor
        in the manifest; returns the step-visible stall in seconds."""
        return self.checkpointer.save(
            self._state(), self.step, cursor=dict(self.cursor), block=block
        )

    def resume_or_init(self, startup_program, program=None):
        """Run startup, then overlay the newest recoverable checkpoint (any
        format, any topology). Returns (step, cursor) and primes the
        supervisor's own step/cursor."""
        step, cursor = resume_or_init(
            self.exe, startup_program, self.root,
            scope=self.scope, program=program or self.program,
        )
        self.step = step
        if cursor:
            self.cursor = dict(cursor)
        return step, self.cursor

    # ---------------------------------------------------------- stepping
    def run_step(self, advance_cursor=True, **run_kwargs):
        """One supervised training step: preemption check → injectable
        hang → watched Executor.run → watchdog/NaN/preemption escalation →
        cursor advance → periodic checkpoint. Returns Executor.run's result."""
        self._check_preempt()
        faults.preempt_self()  # PADDLE_TPU_FAULTS=preempt → SIGTERM to self
        self._check_preempt()
        wd = self._watchdog
        if wd is not None:
            wd.begin_step()
        try:
            faults.hang()  # PADDLE_TPU_FAULTS=hang:ms=... sleeps in-window
            fetches = self.exe.run(**run_kwargs)
        finally:
            stalled = wd.end_step() if wd is not None else False
        if stalled:
            self._emergency("step exceeded deadline %.3fs"
                            % self.step_deadline_s)
        bad = self._nan_this_step(fetches)
        if bad:
            self._escalate_nan()
        else:
            self._bad_steps = 0
            self.step += 1
            if advance_cursor:
                self.cursor["batch_index"] = (
                    int(self.cursor.get("batch_index", 0)) + 1
                )
            if self.ckpt_every and self.step % self.ckpt_every == 0:
                self.save()
        self._check_preempt()
        return fetches

    def next_epoch(self, epoch=None):
        """Advance the data cursor to a new epoch (batch index rewinds)."""
        self.cursor["epoch"] = (
            int(epoch) if epoch is not None
            else int(self.cursor.get("epoch", 0)) + 1
        )
        self.cursor["batch_index"] = 0

    # --------------------------------------------------------- escalation
    def _nan_this_step(self, fetches):
        """Did this step go bad? Either the executor's NaN guard skipped it
        (health counter advanced) or — guard off — a fetched loss is
        non-finite."""
        skipped = health.get("nan_steps_skipped")
        if skipped > self._nan_base:
            self._nan_base = skipped
            return True
        try:
            for f in fetches or ():
                a = np.asarray(f)
                if a.dtype.kind == "f" and not np.isfinite(a).all():
                    return True
        except Exception:
            pass
        return False

    def _escalate_nan(self):
        self._bad_steps += 1
        if self._bad_steps <= self.nan_budget:
            return  # the executor guard's skip-and-decay may still recover
        self._rollbacks += 1
        try:
            _registry().counter(
                "resilience/rollbacks",
                help="NaN-storm rollbacks to the last committed checkpoint",
            ).inc()
        except Exception:
            pass
        health.incr("elastic_rollbacks")
        if self._rollbacks > self.rollback_budget:
            raise FatalError(
                "NaN storm persisted through %d rollback(s) — training "
                "cannot make progress from this state" % self.rollback_budget
            )
        self.rollback()
        self._bad_steps = 0

    def rollback(self):
        """Restore scope + step + data cursor from the newest recoverable
        checkpoint. The poisoned optimizer state is discarded wholesale —
        the executor guard's per-step snapshot cannot help once several
        consecutive steps landed bad updates."""
        self.checkpointer.wait()
        found = async_ckpt.latest_valid_elastic(self.root)
        if found is None:
            raise FatalError(
                "rollback requested but no recoverable checkpoint under %r"
                % self.root
            )
        _step, ckpt_dir = found
        step, arrays, manifest = async_ckpt.load_elastic(ckpt_dir)
        self._overlay(arrays)
        self.step = step
        if manifest.get("cursor"):
            self.cursor = dict(manifest["cursor"])
        self._nan_base = health.get("nan_steps_skipped")

    def _overlay(self, arrays):
        import jax.numpy as jnp

        from ..executor import global_scope

        scope = self.scope or global_scope()
        allowed = None
        if self.program is not None:
            allowed = {v.name for v in self.program.list_vars()}
        for name, arr in arrays.items():
            if allowed is None or name in allowed:
                # jnp.array (copy), NOT jnp.asarray: asarray zero-copy wraps
                # the loaded numpy buffer on the CPU backend, and handing an
                # externally-backed buffer to the donating step jit corrupts
                # same-sized parameters (two outputs land in one buffer)
                scope.set_var(name, jnp.array(arr))

    def _emergency(self, why):
        """Hang/deadline path: persist what we have, then surface a typed
        fatal error for the job scheduler to restart us."""
        health.incr("emergency_checkpoints")
        try:
            self.save(block=True)
        except Exception:
            health.incr("emergency_checkpoint_failed")
        raise FatalError("elastic supervisor: %s" % why)

    # --------------------------------------------------------- preemption
    def _check_preempt(self):
        if not self._preempt:
            return
        try:
            _registry().counter(
                "resilience/preemptions",
                help="SIGTERM/preempt-fault drains handled",
            ).inc()
        except Exception:
            pass
        health.incr("preemptions")
        self.save(block=True)  # emergency commit BEFORE touching the reader
        self.drain()
        raise Preempted(
            "preemption notice honored: checkpoint committed at step %d, "
            "data runtime drained" % self.step
        )

    def drain(self):
        """Stop data producers and discard in-flight batches — the clean
        half-close a preemption grace period allows. Prefers the runtime's
        first-class drain(), falls back to reset(), always best-effort:
        a wedged reader must not block the exit path."""
        r = self.reader
        if r is None:
            return
        for meth in ("drain", "reset"):
            fn = getattr(r, meth, None)
            if fn is None:
                continue
            try:
                fn()
                break
            except Exception:
                continue
        closer = getattr(r, "close", None)
        if closer is not None:
            try:
                closer()
            except Exception:
                pass


# ------------------------------------------------------- elastic resume


def resume_or_init(exe, startup_program, root, scope=None, program=None):
    """Topology-aware trainer-loop entry: run the startup program, then
    overlay the newest recoverable checkpoint under `root` — elastic
    (eckpt-*, shards + replicas, any saved topology) preferred over the
    PR 1 full-replica format (ckpt-*) when both exist at different steps.
    Returns (completed steps, data cursor dict) — (0, {}) for a fresh start.

    Re-sharding is implicit: the overlay lands FULL arrays in the scope and
    the next executor run re-places them via GSPMD state_sharding onto the
    live mesh, so the checkpoint's dp/ep and the resume's dp/ep are
    independent."""
    import jax.numpy as jnp

    from ..executor import global_scope

    exe.run(startup_program)
    scope = scope or global_scope()
    elastic = async_ckpt.latest_valid_elastic(root)
    classic = checkpoint.latest_valid_dir(root)
    e_step = elastic[0] if elastic else -1
    c_step = classic[0] if classic else -1
    if e_step < 0 and c_step < 0:
        return 0, {}
    allowed = None
    if program is not None:
        allowed = {v.name for v in program.list_vars()}
    if e_step >= c_step:
        step, arrays, manifest = async_ckpt.load_elastic(elastic[1])
        cursor = dict(manifest.get("cursor") or {})
    else:
        from .. import io as fluid_io

        step, arrays = c_step, fluid_io.load_arrays(classic[1])
        cursor = {}
    for name, arr in arrays.items():
        if allowed is None or name in allowed:
            # copy, not zero-copy wrap — see Supervisor._overlay
            scope.set_var(name, jnp.array(arr))
    health.incr("resumed_from_checkpoint")
    try:
        _registry().counter(
            "resilience/recoveries",
            help="successful restore-from-checkpoint resumes",
        ).inc()
    except Exception:
        pass
    return step, cursor


def derive_data_shards(cursor, num_hosts, host_id, num_shards):
    """Re-derive this host's data-shard assignment for the cursor's epoch on
    a NEW topology. Pure function of (seed, epoch, num_shards, num_hosts) —
    the same data/sharding.py permutation every host computes independently,
    so after an elastic resize the union over hosts still covers every shard
    exactly once per epoch."""
    from ..data import sharding as dsh

    order = dsh.epoch_shard_order(
        int(num_shards),
        int((cursor or {}).get("seed", 0)),
        int((cursor or {}).get("epoch", 0)),
    )
    return dsh.host_shards(order, int(num_hosts), int(host_id))
