"""Deterministic fault injection.

A FaultPlan is parsed from the PADDLE_TPU_FAULTS env var (or installed
programmatically) and consulted at named hook points in the runtime:

    PADDLE_TPU_FAULTS="rpc_drop:0.1@seed=7,nan_grad:step=12,ckpt_crash:step=20"

Spec grammar (comma-separated `kind:args`, args joined with `@`):
  0.1        probability per hook invocation (seeded RNG; deterministic)
  seed=7     RNG seed for probability draws (default: crc32 of the kind, so
             every kind is deterministic even without an explicit seed)
  step=12    fire exactly on the 12th invocation of the hook (1-based)
  every=5    fire on every 5th invocation
  after=20   invocations <= 20 never fire (offsets step=/every=/prob)
  ms=50      payload for delay-style hooks (milliseconds)
A bare `kind` with no args always fires.

Hook points currently wired (see docs/resilience.md for the full table):
  rpc_drop / rpc_delay      distributed/rpc.py   RPCClient._rpc, pre-send
  master_conn_drop          distributed/master.py  server conn handler
  snapshot_crash            distributed/master.py  between tmp write + rename
  ckpt_crash                io.py save_arrays      between tmp write + rename
  manifest_crash            resilience/checkpoint.py  before MANIFEST commit
  nan_grad                  executor.py            poisons a training step
  worker_die                trainer loops (tests/dist runners)  hard-exits
  eckpt_commit_crash        resilience/async_ckpt.py  before the commit marker
  preempt                   resilience/elastic.py  SIGTERM to self (the cloud
                            preemption notice, injectable)
  hang                      resilience/elastic.py  sleeps spec.ms inside the
                            supervised step window (trips the watchdog)
  replica_kill              serving/server.py      SIGKILLs the serving
                            process before it answers (a replica dying
                            mid-request; the fleet router's failover case)
  conn_reset                serving/server.py      closes the client socket
                            without replying (a half-open connection: the
                            client sees a reset/empty response, the server
                            never processed the request)
  slow_response             serving/server.py      sleeps spec.ms before
                            handling (a browned-out replica; trips the fleet
                            router's attempt timeout + circuit breaker)

Every decision is made from per-kind invocation counters plus a per-kind
seeded RNG, so the same plan + the same call sequence replays the same
faults — the property the resilience tests assert against.
"""

import os
import threading
import time
import zlib
from random import Random

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "active",
    "crash",
    "delay",
    "fires",
    "hang",
    "install",
    "kill_self",
    "preempt_self",
    "reset",
]

ENV_VAR = "PADDLE_TPU_FAULTS"


class InjectedFault(RuntimeError):
    """Raised by crash-style hooks; never raised unless a plan says so."""


class _Spec:
    def __init__(self, kind):
        self.kind = kind
        self.prob = None
        self.step = None
        self.every = None
        self.after = 0
        self.ms = 50.0
        self.seed = None

    def __repr__(self):
        return "_Spec(%s)" % ", ".join(
            "%s=%r" % (k, v) for k, v in sorted(vars(self).items()) if v is not None
        )


def _parse_spec(text):
    kind, _, args = text.strip().partition(":")
    if not kind:
        raise ValueError("empty fault kind in %r" % text)
    spec = _Spec(kind)
    bare = True
    for part in filter(None, (p.strip() for p in args.split("@"))):
        key, eq, val = part.partition("=")
        if not eq:
            spec.prob = float(part)  # "rpc_drop:0.1"
            bare = False
            continue
        if key == "seed":
            spec.seed = int(val)
            continue  # seed alone doesn't make the spec non-bare
        if key in ("step", "every", "after"):
            setattr(spec, key, int(val))
        elif key == "ms":
            spec.ms = float(val)
        else:
            raise ValueError("unknown fault arg %r in %r" % (key, text))
        if key != "ms":
            bare = False
    if bare and spec.prob is None:
        spec.prob = 1.0  # bare kind: always fire
    return spec


class FaultPlan:
    """Parsed fault specs + per-kind counters and RNGs. Thread-safe: hook
    points are hit concurrently from RPC pool workers and server threads."""

    def __init__(self, specs=()):
        self._specs = {s.kind: s for s in specs}
        self._counts = {}
        self._rngs = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text):
        text = (text or "").strip()
        if not text:
            return cls()
        return cls(_parse_spec(p) for p in text.split(",") if p.strip())

    @classmethod
    def from_env(cls, environ=None):
        return cls.parse((environ or os.environ).get(ENV_VAR, ""))

    def __bool__(self):
        return bool(self._specs)

    def kinds(self):
        return sorted(self._specs)

    def spec(self, kind):
        return self._specs.get(kind)

    def count(self, kind):
        """Invocations of the hook so far (for tests/diagnostics)."""
        with self._lock:
            return self._counts.get(kind, 0)

    def fires(self, kind):
        """One hook invocation: advance the counter, decide deterministically."""
        spec = self._specs.get(kind)
        if spec is None:
            return False
        with self._lock:
            n = self._counts.get(kind, 0) + 1
            self._counts[kind] = n
            if n <= spec.after:
                return False
            if spec.step is not None:
                return n - spec.after == spec.step
            if spec.every is not None:
                return (n - spec.after) % spec.every == 0
            rng = self._rngs.get(kind)
            if rng is None:
                seed = spec.seed if spec.seed is not None else zlib.crc32(
                    kind.encode()
                )
                rng = self._rngs[kind] = Random(seed)
            return rng.random() < spec.prob


# --------------------------- process-wide plan ----------------------------

_lock = threading.Lock()
_plan = None
_loaded = False


def active():
    """The installed plan, lazily parsed from PADDLE_TPU_FAULTS on first use.
    Returns None when no faults are configured (the common case: one dict
    probe per hook, no RNG, no lock)."""
    global _plan, _loaded
    if not _loaded:
        with _lock:
            if not _loaded:
                plan = FaultPlan.from_env()
                _plan = plan if plan else None
                _loaded = True
    return _plan


def install(plan):
    """Install a FaultPlan (or a spec string, or None to disable). Tests use
    this for in-process injection; subprocesses inherit the env var instead."""
    global _plan, _loaded
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    with _lock:
        _plan = plan if plan else None
        _loaded = True
    return _plan


def reset():
    """Forget the installed plan; the next hook re-reads the env var."""
    global _plan, _loaded
    with _lock:
        _plan = None
        _loaded = False


def fires(kind):
    plan = active()
    return plan.fires(kind) if plan is not None else False


def crash(kind, detail=""):
    """Crash-style hook: raise InjectedFault when the plan says so. Placed
    between a temp-file write and its rename, this simulates a process dying
    mid-commit — the torn state a recovery path must tolerate."""
    if fires(kind):
        raise InjectedFault(
            "injected fault %r%s" % (kind, (": " + detail) if detail else "")
        )


def delay(kind):
    """Delay-style hook: sleep spec.ms when the plan says so."""
    plan = active()
    if plan is not None and plan.fires(kind):
        spec = plan.spec(kind)
        time.sleep((spec.ms if spec else 50.0) / 1000.0)
        return True
    return False


def preempt_self(kind="preempt"):
    """Preemption-style hook: deliver SIGTERM to this process when the plan
    says so — the injectable stand-in for a cloud preemption notice, so the
    drain path (elastic.Supervisor) soaks under PADDLE_TPU_FAULTS like every
    other failure mode. The signal is delivered synchronously: when this
    returns True the handler has already run."""
    if fires(kind):
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGTERM)
        return True
    return False


def kill_self(kind="replica_kill"):
    """Hard-death hook: deliver SIGKILL to this process when the plan says
    so — no handlers, no drain, no atexit; the closest injectable stand-in
    for an OOM kill or a host loss. Unlike preempt_self there is nothing to
    observe afterwards in-process: the return value only matters when the
    plan did NOT fire."""
    if fires(kind):
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGKILL)
        return True  # pragma: no cover - unreachable after SIGKILL
    return False


def hang(kind="hang"):
    """Hang-style hook: sleep spec.ms when the plan says so. Placed inside
    the supervised step window, a `hang:ms=...` spec past the step deadline
    trips the elastic watchdog exactly like a wedged collective would."""
    return delay(kind)
