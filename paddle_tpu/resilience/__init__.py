"""Fault-tolerant training runtime.

Reference analog: the reference system's resilience was spread across a Go
master (go/master/service.go task re-queue + etcd snapshots), the gRPC layer
(grpc_client.cc FLAGS_max_retry / FLAGS_rpc_deadline), and ad-hoc checkpoint
save ops. Here it is one subsystem with four pieces:

- faults:     deterministic, seeded fault injection (PADDLE_TPU_FAULTS env)
              with hook points in rpc/master/io/executor — CI proves the
              failure paths continuously instead of hoping.
- retry:      one RetryPolicy (bounded attempts, exponential backoff +
              jitter, overall deadline, typed retryable-vs-fatal errors)
              shared by RPCClient, MasterClient and multihost init.
- checkpoint: manifest-based crash-safe checkpoints (per-file checksums,
              atomic MANIFEST.json commit last, keep-last-N GC,
              load_latest_valid skips torn checkpoints) + resume_or_init.
- health:     process-wide counters for degraded-but-alive events (skipped
              NaN steps, rpc retries, requeued tasks) so "survived" is
              observable, not silent.

See docs/resilience.md for the fault spec syntax and the recipe for making
a new subsystem injectable.
"""

from . import checkpoint, faults, health, retry  # noqa: F401
from .checkpoint import (  # noqa: F401
    load_latest_valid,
    resume_or_init,
    save_checkpoint,
    snapshot_persistables,
)
from .faults import FaultPlan, InjectedFault  # noqa: F401
from .retry import DeadlineExceeded, FatalError, RetryPolicy  # noqa: F401

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "RetryPolicy",
    "DeadlineExceeded",
    "FatalError",
    "save_checkpoint",
    "load_latest_valid",
    "resume_or_init",
    "snapshot_persistables",
    "faults",
    "retry",
    "checkpoint",
    "health",
]
