"""Fault-tolerant training runtime.

Reference analog: the reference system's resilience was spread across a Go
master (go/master/service.go task re-queue + etcd snapshots), the gRPC layer
(grpc_client.cc FLAGS_max_retry / FLAGS_rpc_deadline), and ad-hoc checkpoint
save ops. Here it is one subsystem with four pieces:

- faults:     deterministic, seeded fault injection (PADDLE_TPU_FAULTS env)
              with hook points in rpc/master/io/executor — CI proves the
              failure paths continuously instead of hoping.
- retry:      one RetryPolicy (bounded attempts, exponential backoff +
              jitter, overall deadline, typed retryable-vs-fatal errors)
              shared by RPCClient, MasterClient and multihost init.
- checkpoint: manifest-based crash-safe checkpoints (per-file checksums,
              atomic MANIFEST.json commit last, keep-last-N GC,
              load_latest_valid skips torn checkpoints) + resume_or_init.
- health:     process-wide counters for degraded-but-alive events (skipped
              NaN steps, rpc retries, requeued tasks) so "survived" is
              observable, not silent.
- async_ckpt: the elastic checkpoint format — per-host row-range shards +
              neighbor replicas + a rank-0 manifest committed after a
              cross-host barrier; AsyncCheckpointer stalls the step only
              for the device→host copy (docs/resilience.md).
- elastic:    Supervisor (step-deadline watchdog, NaN-storm rollback with a
              bounded budget, SIGTERM/preempt drain) and the topology-aware
              resume_or_init: a checkpoint taken at dp=N/ep=K resumes on
              dp=M/ep=J, with the data cursor re-derived deterministically.

See docs/resilience.md for the fault spec syntax and the recipe for making
a new subsystem injectable.
"""

from . import async_ckpt, checkpoint, elastic, faults, health, retry  # noqa: F401
from .async_ckpt import AsyncCheckpointer  # noqa: F401
from .checkpoint import (  # noqa: F401
    load_latest_valid,
    resume_or_init,
    save_checkpoint,
    snapshot_persistables,
)
from .elastic import Preempted, Supervisor  # noqa: F401
from .faults import FaultPlan, InjectedFault  # noqa: F401
from .retry import DeadlineExceeded, FatalError, RetryPolicy  # noqa: F401

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "RetryPolicy",
    "DeadlineExceeded",
    "FatalError",
    "AsyncCheckpointer",
    "Supervisor",
    "Preempted",
    "save_checkpoint",
    "load_latest_valid",
    "resume_or_init",
    "snapshot_persistables",
    "faults",
    "retry",
    "checkpoint",
    "health",
    "async_ckpt",
    "elastic",
]
