"""Drop-in alias: `import paddle_tpu.fluid as fluid` mirrors `paddle.fluid`
(the reference's python/paddle/fluid/__init__.py public surface)."""

from .. import *  # noqa: F401,F403
from .. import (  # noqa: F401
    backward,
    clip,
    average,
    contrib,
    debugger,
    inference,
    evaluator,
    framework,
    imperative,
    profiler,
    initializer,
    io,
    layers,
    metrics,
    nets,
    optimizer,
    param_attr,
    regularizer,
    transpiler,
    unique_name,
)
from ..transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    InferenceTranspiler,
    memory_optimize,
    release_memory,
)
from ..data_feeder import DataFeeder  # noqa: F401
from ..lod_tensor import create_lod_tensor, create_random_int_lodtensor  # noqa: F401
from ..flags import get_flags, set_flags  # noqa: F401
from ..py_reader import EOFException  # noqa: F401
from ..executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from ..async_executor import AsyncExecutor  # noqa: F401
from ..data_feed_desc import DataFeedDesc  # noqa: F401
from ..parallel_executor import (  # noqa: F401
    BuildStrategy,
    ExecutionStrategy,
    ParallelExecutor,
)
