"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: baobrian/Paddle, see SURVEY.md).

Define-then-run Program IR built by a fluid-compatible Python frontend, lowered
whole-block to XLA via JAX instead of per-op kernel dispatch; SPMD data
parallelism via jax.sharding instead of NCCL; Pallas kernels where XLA fusion
isn't enough.

`import paddle_tpu.fluid as fluid` is a drop-in for `import paddle.fluid`.
"""

from . import (
    backward,
    clip,
    dataset,
    framework,
    initializer,
    io,
    layers,
    metrics,
    nets,
    optimizer,
    param_attr,
    reader,
    regularizer,
    transpiler,
    unique_name,
)
from . import distributed  # noqa: F401
from . import observability  # noqa: F401
from . import resilience  # noqa: F401
from . import profiler  # noqa: F401
from . import imperative  # noqa: F401
from . import debugger  # noqa: F401
from . import average  # noqa: F401
from . import evaluator  # noqa: F401
from . import lod_tensor  # noqa: F401
from . import contrib  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import embedding  # noqa: F401
from . import flags  # noqa: F401
from .flags import get_flags, set_flags
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor
from . import native  # noqa: F401
from .batch import batch
from .data_feeder import DataFeeder
from .py_reader import EOFException
from .backward import append_backward
from .executor import Executor, Scope, global_scope, scope_guard
from .async_executor import AsyncExecutor
from .data_feed_desc import DataFeedDesc
from .framework import (
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    device_guard,
    name_scope,
    program_guard,
)
from .parallel_executor import BuildStrategy, ExecutionStrategy, ParallelExecutor
from .param_attr import ParamAttr, WeightNormParamAttr
from .place import (
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    is_compiled_with_cuda,
)

__version__ = "0.1.0"
